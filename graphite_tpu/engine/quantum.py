"""The quantum engine: lax-barrier-synchronized stepping of all tiles.

The reference bounds target-time skew with the lax-barrier scheme: every
tile that crosses the current quantum boundary blocks at a barrier server
on the MCP until all running tiles arrive, then the boundary advances one
quantum (skipping empty quanta) and everyone releases (reference:
clock_skew_management_schemes/lax_barrier_sync_server.cc:42-160, client
:32-59; SURVEY.md 3.5).

Here the same contract is a reduction: the boundary is recomputed from the
min clock over runnable tiles (a `jnp.min` — under a sharded mesh this is
the `lax.psum`-family collective the north star names), and a quantum step
is ``rounds_per_quantum`` repetitions of (local_advance ; resolve).  Tiles
parked on sync objects (barrier/mutex/recv) are excluded from the min —
the reference likewise excludes sleeping/stalled threads from
isBarrierReached (lax_barrier_sync_server.cc:88-115) — so producers can
run ahead and release them.

``lax`` (no sync) and ``lax_p2p`` (random-pair clamping) map onto the same
engine: the quantum already bounds skew at least as tightly as either, so
they differ only in the modeled sync *cost*, which is zero for all three
(the reference charges no time for barrier waits either — wait time is
simply simulated-time made equal across tiles).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from graphite_tpu.engine.core import local_advance
from graphite_tpu.engine.resolve import resolve
from graphite_tpu.engine.state import (
    PEND_BARRIER, PEND_CBC, PEND_COND, PEND_CSIG, PEND_EX_REQ, PEND_IFETCH,
    PEND_JOIN, PEND_MUTEX, PEND_RECV, PEND_SEND, PEND_SH_REQ, PEND_START,
    SimState, TraceArrays, sampling_enabled, stats_ring_enabled)
from graphite_tpu.engine.vparams import VariantParams, variant_params
from graphite_tpu.params import SimParams
from graphite_tpu.time_base import TIME_MAX


def next_boundary(params: SimParams, state: SimState,
                  vp: VariantParams = None) -> jnp.ndarray:
    """Advance the barrier boundary past the slowest runnable tile,
    skipping empty quanta (reference barrierRelease's quantum skip,
    lax_barrier_sync_server.cc:118-160)."""
    sync_blocked = ((state.pend_kind == PEND_RECV)
                    | (state.pend_kind == PEND_BARRIER)
                    | (state.pend_kind == PEND_MUTEX)
                    | (state.pend_kind == PEND_SEND)
                    | (state.pend_kind == PEND_COND)
                    | (state.pend_kind == PEND_CSIG)
                    | (state.pend_kind == PEND_CBC)
                    | (state.pend_kind == PEND_JOIN)
                    | (state.pend_kind == PEND_START))
    runnable = ~state.done & ~sync_blocked
    clk = state.clock
    if params.miss_chain > 0 and params.fanout_replay:
        # A mid-chain tile's seat clock is FROZEN at its pre-bank value
        # until the drain restores it; its served progress lives in
        # chain_base (the last served element's completion).  Taking the
        # frozen clock pinned the barrier a whole chain-service span
        # behind the machine's real time — empty-ish quanta whose rounds
        # the budget pays for.  chain_base is a sound lower bound on the
        # tile's post-drain clock, so the boundary may advance past it
        # (round 9; off with fanout_replay=0 — the round-8 cadence).
        clk = jnp.where(state.mq_head > 0,
                        jnp.maximum(clk, state.chain_base), clk)
    masked = jnp.where(runnable, clk, TIME_MAX)
    if params.tile_shards > 1:
        # Sharded quantum barrier (round 11): each shard reduces its own
        # T/S tile slice, then a pmin over the mesh axis produces the
        # global minimum — the explicit-collective form of the barrier
        # server, exactly equal to the full-T min (integer clocks, and
        # the shard slices partition the tile axis).
        from graphite_tpu.parallel.mesh import TILE_AXIS
        TL = masked.shape[0] // params.tile_shards
        i = jax.lax.axis_index(TILE_AXIS)
        local = jnp.min(jax.lax.dynamic_slice_in_dim(masked, i * TL, TL, 0))
        min_clock = jax.lax.pmin(local, TILE_AXIS)
    else:
        min_clock = jnp.min(masked)
    q = vp.quantum_ps if vp is not None else jnp.int64(params.quantum_ps)
    nb = (min_clock // q + 1) * q
    return jnp.where(runnable.any(), nb,
                     state.boundary + q).astype(jnp.int64)


def _tel_gauges(st: SimState) -> jnp.ndarray:
    """Engine-health gauge rows (order: obs/metrics.TEL_SERIES) — the
    simulator's own vitals, sampled beside the simulated machine's
    statistics so every run doubles as a profile (PROFILE.md's
    hand-differenced rounds/occupancy numbers, computed in-engine)."""
    k = st.pend_kind
    alive = ~st.done
    mem = ((k == PEND_SH_REQ) | (k == PEND_EX_REQ)
           | (k == PEND_IFETCH)) & alive
    sync = ((k == PEND_BARRIER) | (k == PEND_MUTEX) | (k == PEND_COND)
            | (k == PEND_CSIG) | (k == PEND_CBC) | (k == PEND_JOIN)
            | (k == PEND_START)) & alive
    msg = ((k == PEND_SEND) | (k == PEND_RECV)) & alive
    live_clock = jnp.where(alive, st.clock, TIME_MAX)
    any_alive = alive.any()
    # Under the ThreadScheduler the seat arrays hold only the running
    # subset; cumulative series must fold in the stream store (seat
    # values patched over it, as in all_done) or a rotation would make
    # them non-monotone.
    if st.sched_enabled:
        cursor_all = st.strm_cursor.at[st.seat_stream].set(st.cursor)
        done_all = st.strm_done.at[st.seat_stream].set(st.done)
    else:
        cursor_all, done_all = st.cursor, st.done
    return jnp.stack([
        jnp.sum(cursor_all.astype(jnp.int64)),
        jnp.sum(st.counters.icount),
        jnp.sum(done_all, dtype=jnp.int64),
        jnp.sum(mem, dtype=jnp.int64),
        jnp.sum(sync, dtype=jnp.int64),
        jnp.sum(msg, dtype=jnp.int64),
        st.ctr_quantum,
        st.ctr_window,
        st.ctr_complex,
        st.ctr_conflict,
        st.ctr_resolve,
        jnp.where(any_alive, jnp.min(live_clock), jnp.max(st.clock)),
        jnp.max(st.clock),
    ])


def _maybe_sample(params: SimParams, state: SimState) -> SimState:
    """Record one statistics/progress sample when the quantum boundary
    crosses the sampling interval (the reference samples on barrier
    releases the same way — lax_barrier_sync_server.cc:157-159 notifying
    statistics_thread.cc; series list per statistics_manager.cc:41-114)."""
    from graphite_tpu.engine import cache as cachemod
    from graphite_tpu.engine.state import dword_state
    S = state.stat_time.shape[0]
    interval = jnp.int64(params.stat_interval_ps)
    do = (state.boundary >= state.stat_next) & (state.stat_filled < S)

    def take(st: SimState) -> SimState:
        idx = jnp.minimum(st.stat_filled, S - 1)
        c = st.counters
        if stats_ring_enabled(params):
            if params.shared_l2:
                live = jnp.sum(dword_state(st.dir_word) != 0,
                               dtype=jnp.int64)
            else:
                live = jnp.sum(cachemod.meta_state(st.l2.meta) != 0,
                               dtype=jnp.int64)
            # cache_line_replication analog: total tracked sharer bits
            repl = jnp.sum(jnp.bitwise_count(st.dir_sharers),
                           dtype=jnp.int64)
            scalars = jnp.stack([
                jnp.sum(c.icount), jnp.sum(c.net_mem_flits),
                jnp.sum(c.net_user_flits), jnp.sum(c.dram_reads),
                jnp.sum(c.dram_writes), live, repl,
                jnp.sum(c.net_link_wait_ps),
                # Energy-bearing counters for the power trace
                # ([runtime_energy_modeling/power_trace]; energy.power_trace
                # diffs consecutive samples into per-interval watts).
                jnp.sum(c.l1i_access),
                jnp.sum(c.l1d_read) + jnp.sum(c.l1d_write),
                jnp.sum(c.l2_access), jnp.sum(c.branches),
                jnp.sum(c.dir_sh_req) + jnp.sum(c.dir_ex_req)
                + jnp.sum(c.dir_invalidations)])
            st = st._replace(
                stat_scalars=st.stat_scalars.at[:, idx].set(scalars))
        if params.telemetry_enabled:
            st = st._replace(
                tel_gauges=st.tel_gauges.at[:, idx].set(
                    _tel_gauges(st)),
                tel_cursor=st.tel_cursor.at[idx].set(st.cursor),
                tel_pend=st.tel_pend.at[idx].set(st.pend_kind))
        st = st._replace(
            stat_time=st.stat_time.at[idx].set(st.boundary),
            stat_filled=st.stat_filled + 1,
            stat_next=(st.boundary // interval + 1) * interval)
        if params.progress_enabled:
            st = st._replace(
                stat_icount=st.stat_icount.at[idx].set(c.icount))
        return st

    # lax.cond skips the metadata scans entirely on non-sampling quanta
    # (most of them, at typical interval >> quantum ratios).
    return jax.lax.cond(do, take, lambda st: st, state)


def schedule_rotate(params: SimParams, state: SimState,
                    vp: VariantParams = None) -> SimState:
    """ThreadScheduler seat rotation (reference: thread_scheduler.h:30-56,
    round_robin_thread_scheduler.cc; yield path thread_scheduler.cc:615-660).

    Streams are placed round-robin (strm_tile = s % num_tiles — the
    reference's default placement for uniform spawns; affinity/migration
    are not implemented and rejected nowhere since no event emits them).
    Each tile SEATS one stream; the engine's [T] context arrays are the
    seats.  A seat rotates to the tile's lowest-strm_key waiting stream
    when the seated stream (a) is done, (b) retired a YIELD, (c) parked
    on THREAD_START unspawned, or (d) held the seat past the preemption
    quantum — measured in simulated time (the reference uses host
    seconds, thread_scheduler.cc:632-636).  (d) also rotates streams
    parked on sync objects, so a lock holder queued behind its waiter
    eventually runs (round-robin => no starvation); a rotated-out park
    freezes until the stream is reseated, which skews sync wakeups by at
    most the rotation period — the scheduler's own artifact in the
    reference too.  Memory parks (SH/EX/IFETCH) never rotate: resolve
    serves them within a few rounds.
    """
    T = params.num_tiles
    S = state.strm_cursor.shape[0]
    sst = state.seat_stream                               # [T]
    tiles = jnp.arange(T, dtype=jnp.int32)
    strm_tile = (jnp.arange(S, dtype=jnp.int32) % T)      # static placement

    # Sync the stream store's bookkeeping for seated streams.
    strm_done = state.strm_done.at[sst].set(state.done)
    state = state._replace(strm_done=strm_done)

    k = state.pend_kind
    # Tiles mid-memory-transaction never rotate: parked requests
    # (SH/EX/IFETCH) resolve within a few rounds, and a non-empty miss
    # chain (mq_count > 0, tpu/miss_chain > 0) is tile-resident bank
    # state belonging to the seated stream — rotating under it would
    # drain the old stream's banked requests against the new stream's
    # clock.  EVERY sync park rotates freely (preemption must be able to
    # seat the peer a parked stream is waiting FOR — pinning any sync
    # park can deadlock a waiter queued on the same tile as its waker):
    # mutex/join/recv/send/start wakes are persistent state re-checked on
    # reseat; cond signal/broadcast tokens are durable parked entries
    # whose loss bound covers descheduled streams (resolve_cond lb);
    # barrier releases wake descheduled waiters directly in the stream
    # store (resolve_barrier).
    mem_park = ((k == PEND_SH_REQ) | (k == PEND_EX_REQ)
                | (k == PEND_IFETCH)) | (state.mq_count > 0)
    unspawned_gate = (k == PEND_START) \
        & (state.spawned_at[sst] < 0)
    switch_q = vp.thread_switch_quantum_ps if vp is not None \
        else jnp.int64(params.thread_switch_quantum_ps)
    expired = (state.boundary - state.seat_since) >= switch_q
    give_up = (state.done | state.seat_yield | unspawned_gate
               | expired) & ~mem_park

    # Waiting streams per tile (not seated, not done), FCFS by strm_key.
    seated = jnp.zeros(S, dtype=bool).at[sst].set(True)
    waiting = ~seated & ~strm_done
    BIG = jnp.int64(2**62)
    tbl = jnp.full((T,), BIG, jnp.int64).at[
        jnp.where(waiting, strm_tile, T)].min(state.strm_key, mode="drop")
    has_wait = tbl < BIG
    rotate = give_up & has_wait                           # [T]
    winner = waiting & (tbl[strm_tile] == state.strm_key) \
        & rotate[strm_tile]                               # [S]
    in_s = jnp.zeros(T, dtype=jnp.int32).at[
        jnp.where(winner, strm_tile, T)].max(
        jnp.arange(S, dtype=jnp.int32), mode="drop")      # [T]

    # Save the outgoing context into the store (rotating tiles only).
    out_s = jnp.where(rotate, sst, S)
    def save(store, seat_val):
        return store.at[out_s].set(seat_val, mode="drop")
    max_key = jnp.max(state.strm_key)
    state = state._replace(
        strm_cursor=save(state.strm_cursor, state.cursor),
        strm_clock=save(state.strm_clock, state.clock),
        strm_pend_kind=save(state.strm_pend_kind, state.pend_kind),
        strm_pend_addr=save(state.strm_pend_addr, state.pend_addr),
        strm_pend_issue=save(state.strm_pend_issue, state.pend_issue),
        strm_pend_aux=save(state.strm_pend_aux, state.pend_aux),
        strm_pend_extra=save(state.strm_pend_extra, state.pend_extra),
        strm_done=state.strm_done.at[out_s].set(state.done, mode="drop"),
        # Outgoing stream goes to the back of the queue: keys stay unique
        # because each rotating tile adds a distinct offset.
        strm_key=state.strm_key.at[out_s].set(
            max_key + 1 + tiles.astype(jnp.int64), mode="drop"),
    )
    # Load the incoming context; the core is serial, so the incoming
    # stream's clock can never precede the outgoing one's.
    def load(seat_val, store):
        return jnp.where(rotate, store[in_s], seat_val)
    state = state._replace(
        cursor=load(state.cursor, state.strm_cursor),
        clock=jnp.where(rotate,
                        jnp.maximum(state.strm_clock[in_s], state.clock),
                        state.clock),
        done=load(state.done, state.strm_done),
        pend_kind=load(state.pend_kind, state.strm_pend_kind),
        pend_addr=load(state.pend_addr, state.strm_pend_addr),
        pend_issue=load(state.pend_issue, state.strm_pend_issue),
        pend_aux=load(state.pend_aux, state.strm_pend_aux),
        pend_extra=load(state.pend_extra, state.strm_pend_extra),
        seat_stream=jnp.where(rotate, in_s, sst),
        seat_since=jnp.where(rotate, state.boundary, state.seat_since),
        seat_yield=jnp.where(rotate, False, state.seat_yield),
    )
    # A context switch restores the incoming thread's registers, so its
    # scoreboard starts all-ready — clearing the tile's reg_ready stops
    # the outgoing stream's pending register writes from imposing false
    # RAW stalls on the new stream (iocoom only; [0, T] otherwise).
    # Outstanding LQ/SQ completion times stay: they are absolute-time
    # hardware occupancy the new stream genuinely contends with.
    if state.reg_ready.shape[0] > 0:
        state = state._replace(
            reg_ready=jnp.where(rotate[None, :], 0, state.reg_ready))
    return state


def quantum_step(params: SimParams, state: SimState,
                 trace: TraceArrays,
                 vp: VariantParams = None) -> SimState:
    """One barrier quantum: all tiles advance to the new boundary.

    Sub-rounds of (local_advance ; resolve) repeat while they make
    progress (any event retired or unblocked — the cursor sum moves),
    capped at ``rounds_per_quantum``; quanta whose work drains in one
    sub-round (most of them) pay for one instead of the full cap.

    The progress reductions are HOISTED out of the loop predicate: each
    round computes its post-round sum once in the body and carries
    (prev, cur) as scalars, so the cond is pure scalar compares.  The
    old shape recomputed both full-[T] sums in cond AND body — four
    reduction sweeps per round where one suffices (PROFILE.md: the
    round is fixed-op bound at small T).

    ``vp`` threads the VARIANT timing operands (engine/vparams.py): the
    sweep engine passes a batched pytree under ``vmap``; omitted, it
    derives from ``params`` and traces as constants."""
    if vp is None:
        vp = variant_params(params)
    state = state._replace(boundary=next_boundary(params, state, vp=vp),
                           ctr_quantum=state.ctr_quantum + 1)
    if state.sched_enabled:
        state = schedule_rotate(params, state, vp=vp)

    # Chain cadence (tpu/miss_chain > 0): local_advance is ONE window
    # round + a guarded general slot, so the sub-round loop here is what
    # alternates banking with resolve passes — its cap must admit a full
    # quantum's worth of window rounds, and the progress metric must see
    # mid-chain serves (they move neither cursor nor clock until the
    # chain drains; the memory-stall counter strictly increases on every
    # served element, so it is the monotone witness).
    P = params.miss_chain
    cap = params.rounds_per_quantum if P == 0 \
        else max(params.rounds_per_quantum, params.max_events_per_quantum)

    def progress(st):
        # cursor moves on any retire/bank/unblock; clock moves when a
        # resolve pass drains a miss chain without retiring new events.
        p = jnp.sum(st.cursor.astype(jnp.int64)) + jnp.sum(st.clock)
        if P > 0:
            p = p + jnp.sum(st.counters.mem_stall_ps)
        return p

    def cond(carry):
        i, prev, cur, _st = carry
        return (i < cap) & ((i == 0) | (cur > prev))

    def body(carry):
        i, _prev, cur, st = carry
        st = local_advance(params, st, trace, vp=vp)
        st = resolve(params, st, vp=vp)
        # cur (this round's entry progress) becomes the next compare
        # floor; one reduction pass per round, in the body where it
        # fuses with the round's epilogue.
        return i + 1, cur, progress(st), st

    ff0 = state.ctr_ff if params.fast_forward > 0 else None
    _, _, _, state = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0), jnp.int64(-1), progress(state), state))
    if params.fast_forward > 0:
        # Fast-forwarded-quanta attribution (round 12): a quantum counts
        # once iff some sub-round committed an analytic span — the
        # bench's ff-quanta fraction is ctr_ffq / ctr_quantum.  No
        # boundary patch is needed here: committed spans advance
        # st.clock DIRECTLY (unlike chain serves, which park progress in
        # chain_base), so next_boundary's min-clock already leaps past
        # fast-forwarded progress.
        state = state._replace(
            ctr_ffq=state.ctr_ffq + (state.ctr_ff > ff0).astype(jnp.int64))
    if sampling_enabled(params):
        state = _maybe_sample(params, state)
    return state


def _megastep_impl(params: SimParams, state: SimState,
                   trace: TraceArrays) -> SimState:
    from graphite_tpu.parallel.mesh import shard_wrap
    vp = variant_params(params)

    def run(state, trace):
        def body(st, _):
            return quantum_step(params, st, trace, vp=vp), None

        st, _ = jax.lax.scan(body, state, None,
                             length=params.quanta_per_step)
        return st

    return shard_wrap(params.tile_shards, run, 2)(state, trace)


# State donation is OFF by default.  Chained donation (each window's
# output donated as the next window's input) races buffer lifetime on
# the CPU PJRT client: a long-lived final state can end up referencing
# storage the allocator hands to a LATER compiled program, which then
# scribbles over it — observed as garbage in pass-through leaves
# (period_ps) once more simulations ran in the same process.  The
# corruption reproduces on the pre-round-11 tree with sharding never
# touched, so it is the donation chain itself, not shard_map; it is
# also racy (allocation-order dependent), which is how it survived ten
# rounds of green tests.  GRAPHITE_DONATE_STATE=1 opts back into
# donation (halves peak state memory on HBM-bound runs) for runtimes
# where the chain is known safe; the sharded path never donates.
def state_donation_enabled() -> bool:
    import os
    return os.environ.get("GRAPHITE_DONATE_STATE", "") == "1"


_megastep_donate = partial(jax.jit, static_argnums=0,
                           donate_argnums=1)(_megastep_impl)
_megastep_nodonate = partial(jax.jit, static_argnums=0)(_megastep_impl)


def megastep(params: SimParams, state: SimState,
             trace: TraceArrays) -> SimState:
    """``quanta_per_step`` quantum steps fused into one device program —
    the unit the host driver launches (and the unit `bench.py` times).

    With ``tpu/tile_shards`` > 1 the whole step body runs under
    shard_map (parallel/mesh.shard_wrap): state and trace stay
    replicated, the window walk slices to per-shard tiles inside
    (kernels/window.run_window_sharded), and the quantum barrier is a
    pmin.  At 1 the wrapper is the identity — today's program."""
    if params.shard_state == "resident":
        raise ValueError("tpu/shard_state=resident runs through "
                         "engine/resident.megarun, not the replicated "
                         "quantum program")
    if params.tile_shards <= 1 and state_donation_enabled():
        return _megastep_donate(params, state, trace)
    return _megastep_nodonate(params, state, trace)


def megarun_loop(params: SimParams, vp: VariantParams, state: SimState,
                 trace: TraceArrays, max_quanta,
                 masked: bool = True) -> SimState:
    """The megarun while_loop body, vp-threaded and UNJITTED — shared by
    the serial ``megarun`` wrapper below (vp traces as constants) and the
    sweep engine's vmapped invocation (graphite_tpu/sweep/batch.py, vp a
    [V]-batched operand pytree).

    With ``masked`` the body commits a quantum_step's result only where
    the run was not already complete: under ``vmap`` the loop runs to
    the SLOWEST variant and the mask freezes finished lanes bit-exactly
    — their clocks, counters, and quantum counts stay what a solo run
    would have produced.  The serial wrapper passes ``masked=False``:
    its scalar cond already gates the body on ~done, so the mask could
    only ever select the new state — skipping it is result-identical
    and avoids a whole-SimState select per quantum (pass-through state
    copies are a measured per-round cost on TPU; see resolve()'s
    gating note).
    """
    start = state.ctr_quantum
    budget = jnp.asarray(max_quanta, jnp.int64)

    # The all_done reduction is carried: computed once per quantum at the
    # END of the body (where it fuses with the quantum's epilogue ops)
    # instead of re-sweeping the done/strm_done arrays in the cond — the
    # cond then reads two scalars.
    def cond(carry):
        st, done = carry
        return (~done) & ((st.ctr_quantum - start) < budget)

    def body(carry):
        st, done = carry
        new = quantum_step(params, st, trace, vp=vp)
        if masked:
            st = jax.tree_util.tree_map(
                lambda o, n: jnp.where(done, o, n), st, new)
        else:
            st = new
        return st, st.all_done()

    state, _ = jax.lax.while_loop(cond, body, (state, state.all_done()))
    return state


def _megarun_impl(params: SimParams, state: SimState, trace: TraceArrays,
                  max_quanta) -> SimState:
    from graphite_tpu.parallel.mesh import shard_wrap

    def run(state, trace, vp, mq):
        return megarun_loop(params, vp, state, trace, mq, masked=False)

    return shard_wrap(params.tile_shards, run, 4)(
        state, trace, variant_params(params), max_quanta)


_megarun_donate = partial(jax.jit, static_argnums=0,
                          donate_argnums=1)(_megarun_impl)
_megarun_nodonate = partial(jax.jit, static_argnums=0)(_megarun_impl)


def megarun(params: SimParams, state: SimState, trace: TraceArrays,
            max_quanta) -> SimState:
    """Run quantum steps ON DEVICE until the simulation completes or
    ``max_quanta`` quanta elapse — one host dispatch per polling window.

    ``megastep`` pays one host->device dispatch per ``quanta_per_step``
    quanta; under a tunneled accelerator each dispatch is a network
    round trip, and at small tile counts those round trips — not device
    compute — dominated bench wall-clock (r5 profile).  The body here is
    the SAME ``quantum_step``, so timing semantics are bit-identical;
    the while_loop just moves the step loop and the done check across
    the dispatch boundary.  ``max_quanta`` is a TRACED scalar so every
    window size shares one compiled program (the warm-up run must warm
    the real program).

    Sharding rides the same wrapper as ``megastep``: with
    ``tpu/tile_shards`` > 1 the loop body (window slicing, the pmin
    barrier, the replicated resolve) runs under shard_map; at 1 the
    wrapper is the identity and this is today's program, bit for bit.
    State donation is opt-in and 1-only (see the note above
    ``state_donation_enabled``).
    """
    if params.shard_state == "resident":
        raise ValueError("tpu/shard_state=resident runs through "
                         "engine/resident.megarun, not the replicated "
                         "quantum program")
    if params.tile_shards <= 1 and state_donation_enabled():
        return _megarun_donate(params, state, trace, max_quanta)
    return _megarun_nodonate(params, state, trace, max_quanta)
