"""Host-side simulation driver + end-of-run summary.

Driver: the role of the reference's Simulator singleton + sim-thread
manager (reference: common/system/simulator.cc:83-203) collapses to a small
host loop launching fused device steps (engine/quantum.py) and polling
termination — there are no server threads to start or join.

Summary: the reference aggregates every component's outputSummary() into
one ``sim.out`` on process 0 (reference: simulator.cc:135-170,
tile_manager_summary.cc); here the counters already live in device arrays,
so the summary is one device->host transfer + formatting.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import numpy as np

from graphite_tpu.config import Config
from graphite_tpu.engine.quantum import megarun, megastep  # noqa: F401
# (megastep stays exported: the sharded mesh path, the multi-host dryrun,
# and __graft_entry__ drive it directly)
from graphite_tpu.engine.state import SimState, TraceArrays, make_state
from graphite_tpu.events.schema import Trace
from graphite_tpu.params import SimParams
from graphite_tpu.time_base import ps_to_ns


class SimSummary:
    """Counter roll-up with sim.out-style rendering."""

    def __init__(self, params: SimParams, state: SimState,
                 host_seconds: float, steps: int,
                 ingest_stats: Optional[Dict] = None):
        self.params = params
        self.host_seconds = host_seconds
        self.steps = steps
        # Streaming-ingest accounting (engine/ingest.py stats dict);
        # None for whole-trace runs.
        self.ingest_stats = ingest_stats
        self.quanta = int(state.ctr_quantum)
        self.clock = np.asarray(state.clock)
        # Per-STREAM done (== per-tile when the scheduler is off).  A
        # seat only shows its currently-scheduled stream, so under the
        # ThreadScheduler the store's flags are patched with the seated
        # streams' live values — the summary reports EVERY stream's
        # completion, not one all-streams scalar (VERDICT weak #9: the
        # old reduction hid which stream was stuck).
        if state.sched_enabled:
            self.done = np.asarray(
                state.strm_done.at[state.seat_stream].set(state.done))
        else:
            self.done = np.asarray(state.done)
        self.period_ps = np.asarray(state.period_ps)
        self.stat_filled = int(state.stat_filled)
        self.stat_time = np.asarray(state.stat_time)
        self.stat_scalars = np.asarray(state.stat_scalars)
        self.stat_icount = np.asarray(state.stat_icount)
        self.counters: Dict[str, np.ndarray] = {
            f: np.asarray(getattr(state.counters, f))
            for f in state.counters._fields
        }
        # Round-12 adaptive-fidelity attribution: engaged analytic
        # rounds, quanta with >= 1 fast-forwarded span, events priced
        # in closed form (all zero when tpu/fast_forward = 0).
        self.ff_rounds = int(state.ctr_ff)
        self.ff_quanta = int(state.ctr_ffq)
        self.ff_events = int(state.ff_events)
        self.vm_brk = int(state.vm_brk)
        self.vm_mmap_bytes = int(state.vm_mmap_bytes)
        self.vm_munmap_bytes = int(state.vm_munmap_bytes)
        self.tel_gauges = np.asarray(state.tel_gauges)
        self.tel_cursor = np.asarray(state.tel_cursor)
        self.tel_pend = np.asarray(state.tel_pend)

    # ------------------------------------------------------------ metrics

    @property
    def completion_time_ps(self) -> int:
        return int(self.clock.max())

    @property
    def total_instructions(self) -> int:
        return int(self.counters["icount"].sum())

    @property
    def simulated_mips(self) -> float:
        if self.host_seconds <= 0:
            return float("inf")
        return self.total_instructions / self.host_seconds / 1e6

    STAT_SERIES = ("icount", "net_mem_flits", "net_user_flits",
                   "dram_reads", "dram_writes", "live_l2_lines",
                   "sharer_copies", "net_link_wait_ps")

    @property
    def _stats_filled(self) -> int:
        """Samples recorded into the stat_scalars series ring — 0 when
        only telemetry sampled (tel_* arrays have their own series; the
        stat_scalars ring is a 1-column dummy then)."""
        from graphite_tpu.engine.state import stats_ring_enabled
        return self.stat_filled if stats_ring_enabled(self.params) else 0

    def power_trace(self) -> Dict[str, np.ndarray]:
        """Per-interval power from the sampled energy counters — the
        reference's [runtime_energy_modeling/power_trace] file
        (carbon_sim.cfg:141-145, TileEnergyMonitor)."""
        from graphite_tpu.energy import power_trace
        return power_trace(self.params, self.stat_time, self.stat_scalars,
                           self._stats_filled)

    def write_power_trace(self, path: str) -> None:
        pt = self.power_trace()
        with open(path, "w") as f:
            f.write("time_ns,dynamic_w,leakage_w,total_w\n")
            for i in range(len(pt["time_ns"])):
                f.write(f"{pt['time_ns'][i]:.1f},{pt['dynamic_w'][i]:.6f},"
                        f"{pt['leakage_w'][i]:.6f},{pt['total_w'][i]:.6f}\n")

    def stats_trace(self) -> Dict[str, np.ndarray]:
        """Periodic samples taken at quantum boundaries (the reference's
        StatisticsManager trace files + progress trace, as arrays).
        Cumulative series; differentiate for rates."""
        n = self._stats_filled
        out = {"time_ps": self.stat_time[:n]}
        for i, name in enumerate(self.STAT_SERIES):
            out[name] = self.stat_scalars[i, :n]
        if self.params.progress_enabled:
            out["tile_icount"] = self.stat_icount[:n]
        return out

    def write_stats_csv(self, path: str) -> None:
        """Statistics-trace file (reference: statistics_manager.cc trace
        file output, one row per sample)."""
        tr = self.stats_trace()
        cols = [k for k in tr if k != "tile_icount"]
        with open(path, "w") as f:
            f.write(",".join(cols) + "\n")
            for i in range(len(tr["time_ps"])):
                f.write(",".join(str(int(tr[c][i])) for c in cols) + "\n")

    def write_progress_trace(self, path: str) -> None:
        """Per-tile progress CSV (reference: pin/progress_trace.cc —
        (time, instruction count) rows per tile)."""
        if not self.params.progress_enabled:
            raise ValueError(
                "progress trace was not recorded: set "
                "progress_trace/enabled=true before the run")
        tr = self.stats_trace()
        with open(path, "w") as f:
            f.write("time_ps," + ",".join(
                f"tile{t}" for t in range(self.params.num_tiles)) + "\n")
            for i in range(len(tr["time_ps"])):
                row = tr["tile_icount"][i]
                f.write(str(int(tr["time_ps"][i])) + ","
                        + ",".join(str(int(v)) for v in row) + "\n")

    # -------------------------------------------------------- telemetry
    # ([telemetry] engine-health round metrics; graphite_tpu/obs)

    def telemetry_trace(self) -> Optional[Dict[str, np.ndarray]]:
        """Sampled engine-health gauge series (obs/metrics.TEL_SERIES
        rows over the shared quantum-boundary sample ring); None when
        [telemetry] was disabled for the run."""
        if not self.params.telemetry_enabled:
            return None
        from graphite_tpu.obs.metrics import TEL_SERIES
        n = self.stat_filled
        out = {"time_ps": self.stat_time[:n]}
        for i, name in enumerate(TEL_SERIES):
            out[name] = self.tel_gauges[i, :n]
        return out

    def tel_cursor_trace(self) -> Optional[np.ndarray]:
        """[samples, T] per-tile trace-cursor snapshots (per-tile
        progress in events); None when telemetry was disabled."""
        if not self.params.telemetry_enabled:
            return None
        return self.tel_cursor[:self.stat_filled]

    def tel_pend_trace(self) -> Optional[np.ndarray]:
        """[samples, T] per-tile pend_kind snapshots (occupancy / stall
        attribution); None when telemetry was disabled."""
        if not self.params.telemetry_enabled:
            return None
        return self.tel_pend[:self.stat_filled]

    def run_report(self, tracer=None, workload: Optional[str] = None,
                   extra: Optional[Dict] = None) -> Dict:
        """Machine-readable RunReport dict (obs/export.build_run_report):
        the JSON superset of render(), plus host spans and the sampled
        telemetry series."""
        from graphite_tpu.obs.export import build_run_report
        return build_run_report(self, tracer=tracer, workload=workload,
                                extra=extra)

    def write_telemetry(self, dirpath: str, tracer=None,
                        workload: Optional[str] = None,
                        prefix: str = "run") -> Dict[str, str]:
        """Write the RunReport + Chrome trace-event JSON artifacts."""
        from graphite_tpu.obs.export import write_telemetry_dir
        return write_telemetry_dir(dirpath, self, tracer=tracer,
                                   workload=workload, prefix=prefix)

    def energy(self):
        """Analytic McPAT/DSENT-shaped energy breakdown (graphite_tpu.
        energy) on the final counters at each module's current V/f."""
        from graphite_tpu.energy import compute_energy
        return compute_energy(self.params, self.counters,
                              self.completion_time_ps, self.period_ps)

    def to_dict(self) -> Dict:
        agg = {k: int(v.sum()) for k, v in self.counters.items()}
        out = {
            "num_tiles": self.params.num_tiles,
            "completion_time_ns": ps_to_ns(self.completion_time_ps),
            "host_seconds": self.host_seconds,
            "device_steps": self.steps,
            "quanta": self.quanta,
            "total_instructions": self.total_instructions,
            "simulated_mips": self.simulated_mips,
            "all_done": bool(self.done.all()),
            # Per-stream completion (VERDICT weak #9): how many of the
            # trace's streams retired DONE — with the ThreadScheduler
            # this counts descheduled streams too, so a stuck run shows
            # WHICH fraction finished instead of one false/true.
            "streams_done": int(self.done.sum()),
            "num_streams": int(self.done.shape[0]),
            "aggregate": agg,
        }
        if self.params.fast_forward > 0:
            out["ff_rounds"] = self.ff_rounds
            out["ff_quanta"] = self.ff_quanta
            out["ff_events"] = self.ff_events
            out["ff_quanta_frac"] = round(
                self.ff_quanta / max(self.quanta, 1), 4)
        if self.params.enable_power_modeling:
            out["energy"] = self.energy().to_dict()
        vm_sec = self.vm_summary()
        if vm_sec is not None:
            out["vm"] = vm_sec
        ing = self.ingest_section()
        if ing is not None:
            out["ingest"] = ing
        return out

    def ingest_section(self) -> Optional[Dict]:
        """Streaming-ingest roll-up (None for whole-trace runs): the
        engine/ingest.py stats plus the stall FRACTION of this run's
        host wall clock — the bench's keeps-up metric (near zero when
        the prefetch hid every seam)."""
        if self.ingest_stats is None:
            return None
        out = dict(self.ingest_stats)
        out["ingest_stall_fraction"] = round(
            out["ingest_stall_seconds"] / self.host_seconds, 6) \
            if self.host_seconds > 0 else 0.0
        return out

    def vm_summary(self):
        """Simulated address-space accounting (engine/vm.summarize;
        reference vm_manager.cc segments) — None when the trace made no
        memory-management syscalls."""
        from graphite_tpu.engine import vm as vmmod
        return vmmod.summarize(
            self.params.num_tiles, self.params.stack_base,
            self.params.stack_size_per_core, self.vm_brk,
            self.vm_mmap_bytes, self.vm_munmap_bytes)

    def render(self) -> str:
        c = self.counters
        agg = {k: v.sum() for k, v in c.items()}
        lines = []
        w = 46
        def row(k, v):
            lines.append(f"    {k:<{w}}: {v}")
        lines.append("[general]")
        row("Total Tiles", self.params.num_tiles)
        row("Completion Time (in ns)", f"{ps_to_ns(self.completion_time_ps):.1f}")
        row("Streams Completed",
            f"{int(self.done.sum())} / {int(self.done.shape[0])}")
        row("Total Instructions", agg["icount"])
        row("Host Time (in s)", f"{self.host_seconds:.3f}")
        row("Simulated MIPS", f"{self.simulated_mips:.3f}")
        if self.params.fast_forward > 0:
            lines.append("[fast_forward]")
            row("Analytic Rounds", self.ff_rounds)
            row("Fast-Forwarded Quanta",
                f"{self.ff_quanta} / {self.quanta}")
            row("Events Priced In Closed Form", self.ff_events)
        lines.append("[core]")
        row("Total Instructions", agg["icount"])
        row("Branches", agg["branches"])
        row("Branch Mispredictions", agg["mispredicts"])
        lines.append("[l1_icache]")
        row("Cache Accesses", agg["l1i_access"])
        row("Cache Misses", agg["l1i_miss"])
        lines.append("[l1_dcache]")
        row("Read Accesses", agg["l1d_read"])
        row("Read Misses", agg["l1d_read_miss"])
        row("Write Accesses", agg["l1d_write"])
        row("Write Misses", agg["l1d_write_miss"])
        lines.append("[l2_cache]")
        row("Cache Accesses", agg["l2_access"])
        row("Cache Misses", agg["l2_miss"])
        if self.params.track_miss_types:
            row("Cold Misses", agg["l2_miss_cold"])
            row("Capacity Misses", agg["l2_miss_capacity"])
            row("Sharing Misses", agg["l2_miss_sharing"])
        lines.append("[dram_directory]")
        row("Shared Requests", agg["dir_sh_req"])
        row("Exclusive Requests", agg["dir_ex_req"])
        row("Invalidations", agg["dir_invalidations"])
        row("Writebacks", agg["dir_writebacks"])
        row("Cache-to-Cache Forwards", agg["dir_forwards"])
        row("Evictions", agg["dir_evictions"])
        row("Conflict-Round Deferrals", agg["dir_deferrals"])
        lines.append("[dram]")
        row("Reads", agg["dram_reads"])
        row("Writes", agg["dram_writes"])
        lines.append("[network (memory)]")
        row("Packets", agg["net_mem_pkts"])
        row("Flits", agg["net_mem_flits"])
        row("Link Contention Delay (in ns, total)",
            f"{ps_to_ns(agg['net_link_wait_ps']):.1f}")
        lines.append("[network (user)]")
        row("Packets", agg["net_user_pkts"])
        row("Flits", agg["net_user_flits"])
        lines.append("[sync]")
        row("Barriers", agg["barriers"])
        row("Mutex Acquires", agg["mutex_acquires"])
        row("Cond Waits", agg["cond_waits"])
        row("Cond Signals/Broadcasts", agg["cond_signals"])
        row("Messages Sent", agg["sends"])
        row("Messages Received", agg["recvs"])
        lines.append("[threads]")
        row("Spawns", agg["spawns"])
        row("Joins", agg["joins"])
        lines.append("[syscalls]")
        row("Syscalls", agg["syscalls"])
        row("Syscall Time (in ns, total)",
            f"{ps_to_ns(agg['syscall_ps']):.1f}")
        vm_sec = self.vm_summary()
        if vm_sec is not None:
            lines.append("[vm]")
            row("Data Segment (brk) Bytes", vm_sec["data_segment_bytes"])
            row("Dynamic Segment (mmap) Bytes", vm_sec["mmap_bytes"])
            row("Unmapped (munmap) Bytes", vm_sec["munmap_bytes"])
            row("Stack Segment Bytes", vm_sec["stack_segment_bytes"])
            if vm_sec["brk_overflow"] or vm_sec["dynamic_overflow"]:
                row("SEGMENT OVERFLOW", ", ".join(
                    name for name, flag
                    in (("brk", vm_sec["brk_overflow"]),
                        ("dynamic", vm_sec["dynamic_overflow"])) if flag))
        lines.append("[stalls]")
        row("Memory Stall (in ns, total)", f"{ps_to_ns(agg['mem_stall_ps']):.1f}")
        row("Sync Stall (in ns, total)", f"{ps_to_ns(agg['sync_stall_ps']):.1f}")
        if self.params.enable_power_modeling:
            e = self.energy()
            seconds = max(self.completion_time_ps * 1e-12, 1e-30)
            lines.append("[energy]")
            for name in ("core", "l1i", "l1d", "l2", "directory", "dram",
                         "network", "leakage"):
                row(f"{name.capitalize()} Energy (in uJ)",
                    f"{float(getattr(e, name).sum()) * 1e6:.3f}")
            row("Total Energy (in uJ)", f"{float(e.total.sum()) * 1e6:.3f}")
            row("Average Power (in W)",
                f"{float(e.total.sum()) / seconds:.3f}")
            row("Tile Area (in mm^2)", f"{e.area_mm2_per_tile:.3f}")
        return "\n".join(lines) + "\n"


class DeadlockError(RuntimeError):
    """No tile made progress across a full polling window — the trace is
    waiting on something that can never happen (e.g. mismatched barrier
    participant counts)."""


class Simulator:
    """Headless simulator-as-library (the MODE= pattern of the reference's
    unit tests, tests/unit/shared_mem_basic/Makefile:6)."""

    def __init__(self, params: SimParams, trace: Trace):
        # More trace streams than tiles engages the ThreadScheduler
        # (round-robin multi-thread-per-core, reference
        # thread_scheduler.h:30-56); fewer is an error, as is exceeding
        # tiles x general/max_threads_per_core (checked in make_state).
        if trace.num_tiles < params.num_tiles:
            raise ValueError(
                f"trace has {trace.num_tiles} streams, params expect "
                f"at least {params.num_tiles}")
        from graphite_tpu.obs import span
        self.params = params
        # Streaming segmented ingest (round 16, trace/segment_events):
        # only two fixed-capacity segments are ever device-resident
        # (active + prefetch) and the host feeds the device across
        # megarun boundaries — traces larger than HBM simulate whole.
        # engine/ingest.py documents the bit-identity contract.
        self.ingest = None
        if params.segment_events > 0:
            from graphite_tpu.engine import ingest as ingest_mod
            self.ingest = ingest_mod.StreamingIngest(params, trace)
            self.trace = self.ingest.arrays
        else:
            with span("trace.device_upload", events=trace.ops.size):
                self.trace = TraceArrays.from_trace(trace)
        # CAPI channel state is O(T^2); only allocate it when the trace
        # actually messages (scan once, host-side).
        from graphite_tpu.isa import EventOp
        ops = np.asarray(trace.ops)
        has_capi = bool(((ops == int(EventOp.SEND))
                         | (ops == int(EventOp.RECV))).any())
        if has_capi and trace.num_tiles > params.num_tiles:
            raise ValueError(
                "CAPI SEND/RECV with multi-thread-per-core scheduling is "
                "not supported yet (channel state is tile-addressed)")
        with span("state.alloc", num_tiles=params.num_tiles):
            self.state = make_state(params, has_capi=has_capi,
                                    num_streams=trace.num_tiles)
        self.steps = 0
        self.host_seconds = 0.0
        # True when the last run() exited on an expired wall-clock
        # budget (state intact at a window boundary, checkpointable).
        self.preempted = False

    def run(self, max_steps: Optional[int] = None,
            poll_every: int = 8,
            budget_s: Optional[float] = None) -> SimSummary:
        """Run megasteps until every tile is DONE (or max_steps).

        ``budget_s``: wall-clock budget — on expiry the loop exits at
        the next window boundary with ``self.preempted`` True; a
        save_checkpoint / restore_checkpoint / run() sequence then
        continues bit-identically (resume determinism is the
        checkpoint module's contract)."""
        from graphite_tpu.log import get_logger
        from graphite_tpu.obs import span
        lg = get_logger("driver")
        lg.info("run: %d tiles, %d events/tile, protocol=%s",
                self.params.num_tiles, self.trace.num_events,
                self.params.protocol)
        self.preempted = False
        t0 = time.perf_counter()
        last_progress = None
        qps = self.params.quanta_per_step
        quanta = 0
        first_dispatch = True
        while True:
            # One device dispatch per polling window: megarun loops
            # quantum steps ON DEVICE and exits early once every stream
            # is done — the per-megastep dispatch round trips (a network
            # hop each under a tunneled accelerator) used to dominate
            # small-T wall clock.
            window = poll_every if max_steps is None \
                else max(min(poll_every, max_steps - self.steps), 0)
            if window == 0:
                break
            # The first window pays jit trace+compile (or cache load) on
            # top of device time; its span is named apart so compile
            # cost is attributable in the exported host track.
            with span("sim.compile+window" if first_dispatch
                      else "sim.window", quanta=window * qps):
                om_any = False
                if self.ingest is not None:
                    from graphite_tpu.engine import ingest as ingest_mod
                    # Dispatch is async; the prefetch's host slice +
                    # upload below overlaps the device compute — that
                    # overlap IS the double buffer.
                    self.state, om = ingest_mod.megarun(
                        self.params, self.state, self.trace, window * qps)
                    self.ingest.start_prefetch()
                    done, cursor_sum, clock_sum, quanta, om_any = \
                        jax.device_get(
                            (self.state.all_done(),
                             self.state.cursor.sum(),
                             self.state.clock.sum(),
                             self.state.ctr_quantum, om.any()))
                elif self.params.shard_state == "resident":
                    from graphite_tpu.engine import resident
                    self.state = resident.megarun(
                        self.params, self.state, self.trace, window * qps)
                else:
                    self.state = megarun(self.params, self.state,
                                         self.trace, window * qps)
                if self.ingest is None:
                    done, cursor_sum, clock_sum, quanta = jax.device_get(
                        (self.state.all_done(), self.state.cursor.sum(),
                         self.state.clock.sum(), self.state.ctr_quantum))
            first_dispatch = False
            if bool(om_any):
                # Segment seam: the megarun stopped at a quantum
                # boundary with some stream needing its next segment.
                om_np, cur_np = jax.device_get((om, self.state.cursor))
                self.trace = self.ingest.swap(om_np, cur_np)
            # Megastep-equivalent step count (reporting + max_steps
            # budget), from the quanta the device actually ran.
            self.steps = -(-int(quanta) // qps)
            if bool(done):
                break
            if max_steps is not None and self.steps >= max_steps:
                break
            if budget_s is not None \
                    and time.perf_counter() - t0 >= budget_s:
                self.preempted = True
                break
            # Segment swaps count as progress: a seam megarun may
            # commit zero quanta (the very first quantum needed data),
            # which is forward motion as long as bases advanced — the
            # ingest itself raises on a no-progress swap.
            base_sum = self.ingest.base_sum if self.ingest is not None \
                else 0
            progress = (int(cursor_sum), int(clock_sum), base_sum)
            if progress == last_progress:
                raise DeadlockError(
                    f"no progress after {self.steps} steps "
                    f"(cursor_sum={cursor_sum}, clock_sum={clock_sum})")
            last_progress = progress
        self.host_seconds = time.perf_counter() - t0
        # Quanta are exact; the megastep-equivalent count would bill a
        # partial early-exit window as a full megastep (ADVICE r5).
        lg.info("run finished: %d quanta (%d-quanta windows), %.2f host-s",
                int(quanta), qps, self.host_seconds)
        return self.summary()

    def summary(self) -> SimSummary:
        return SimSummary(self.params, self.state, self.host_seconds,
                          self.steps,
                          ingest_stats=self.ingest.stats()
                          if self.ingest is not None else None)

    # -------------------------------------------------- checkpoint/resume
    # (absent in the reference — SURVEY.md section 5.4; pure-array state
    # makes it a flatten+save here)

    def save_checkpoint(self, path: str) -> None:
        from graphite_tpu.engine.checkpoint import save_checkpoint
        # Streamed runs checkpoint at segment seams (run() only returns
        # at megarun boundaries, which every seam is): the ingest frame
        # rides beside the state so resume re-slices the same segments.
        ingest = None
        if self.ingest is not None:
            ingest = {"base": self.ingest.bases,
                      "segment_events": self.ingest.plan.segment_events,
                      "n_total": self.ingest.plan.n_total}
        save_checkpoint(path, self.state, self.steps, ingest=ingest)

    def restore_checkpoint(self, path: str) -> None:
        from graphite_tpu.engine.checkpoint import load_checkpoint
        self.state, self.steps = load_checkpoint(path, self.params)
        if self.ingest is not None:
            from graphite_tpu.engine.checkpoint import load_ingest
            frame = load_ingest(path)
            if frame is not None:
                if frame["n_total"] != self.ingest.plan.n_total:
                    raise ValueError(
                        f"streamed checkpoint was cut from a "
                        f"{frame['n_total']}-event trace; this trace "
                        f"has {self.ingest.plan.n_total}")
                bases = frame["base"]
            else:
                # Whole-trace (v26/v27 non-streamed) checkpoint into a
                # streamed run: derive bases from the restored cursors —
                # base placement never affects values, only which
                # columns are resident, so any base <= cursor (capped)
                # resumes bit-identically.
                bases = np.asarray(self.state.cursor)
            self.ingest.rebase(bases)
            self.trace = self.ingest.arrays
        if self.params.shard_state == "resident" \
                and self.params.tile_shards > 1:
            # Checkpoints are whole-array (the save seam gathers); a
            # resident run re-places its restored state tile-sharded.
            from graphite_tpu.parallel import mesh as meshmod
            mesh = meshmod.make_mesh(
                jax.devices()[:self.params.tile_shards])
            self.state = meshmod.resident_place(
                self.state, mesh, self.params.num_tiles)


def run_simulation(params: SimParams, trace: Trace,
                   max_steps: Optional[int] = None) -> SimSummary:
    return Simulator(params, trace).run(max_steps=max_steps)


def run_simulation_from_trace(cfg: Config, trace_path: str) -> SimSummary:
    """CLI entry (graphite_tpu.cli 'run')."""
    from graphite_tpu.obs import span
    with span("trace.load", path=trace_path):
        trace = Trace.load(trace_path)
    with span("params.resolve"):
        params = SimParams.from_config(cfg, num_tiles=trace.num_tiles)
    with span("sim.run", num_tiles=params.num_tiles):
        return run_simulation(params, trace)
