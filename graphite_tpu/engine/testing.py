"""Test/debug helpers for the engine (the headless 'simulator-as-library'
usage the reference's unit tests rely on, reference:
tests/unit/shared_mem_basic/shared_mem_basic.cc:16-44)."""

from __future__ import annotations

from typing import Iterable

import jax.numpy as jnp

from graphite_tpu.engine import cache as cachemod
from graphite_tpu.engine.state import SimState
from graphite_tpu.params import CacheParams


def warm_cache(cache: cachemod.CacheArrays, cp: CacheParams, tile: int,
               lines: Iterable[int],
               state_val: int = cachemod.S) -> cachemod.CacheArrays:
    """Pre-install lines into one tile's cache (eager, host-side; for tests
    that want warm-hit timing without modeling the cold misses)."""
    for line in lines:
        sidx = int(line) % cp.num_sets
        # find a free way (or overwrite way 0)
        ways = cachemod.word_state(cache.word[:, tile, sidx])
        free = int(jnp.argmax(ways == cachemod.I)) \
            if bool((ways == cachemod.I).any()) else 0
        cache = cache._replace(
            word=cache.word.at[free, tile, sidx].set(
                int(cachemod.pack_word(int(line), 0, state_val))),
        )
    return cache


def warm_icache_for_trace(state: SimState, params, trace) -> SimState:
    """Install every COMPUTE/BRANCH line of the trace into L1I (all tiles)."""
    import numpy as np
    from graphite_tpu.isa import EventOp
    line_bits = params.line_size.bit_length() - 1
    ops = np.asarray(trace.ops)
    addr = np.asarray(trace.addr)
    arg2 = np.asarray(trace.arg2)
    l1i = state.l1i
    for t in range(params.num_tiles):
        lines = set()
        sel = (ops[t] == EventOp.COMPUTE) | (ops[t] == EventOp.BRANCH)
        for a, n in zip(addr[t][sel], arg2[t][sel]):
            start = int(a) >> line_bits
            end = int(a + max(int(n), 1) * 4) >> line_bits
            lines.update(range(start, end + 1))
        l1i = warm_cache(l1i, params.l1i, t, lines)
    return state._replace(l1i=l1i)
