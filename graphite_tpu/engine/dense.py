"""Dense one-hot primitives for TPU-friendly indexed access.

TPU lowering rationale (measured on v5e): XLA lowers real gather/scatter
ops over small arrays to a serialized per-index-row loop (~10 us per op
regardless of payload), while dense masked selects/reduces lower to fused
vector ops at HBM bandwidth (<1 us for this engine's array sizes).  Every
hot-path operation indexed by a [T]-shaped vector therefore goes through a
one-hot mask plus a masked reduce (gather) or masked select (scatter).

Dense one-hots are O(rows * bins) memory; callers that bin into large
spaces (the election hash tables) fall back to real scatters above
``DENSE_MAX_ELEMS`` — at those sizes the serialized scatter is amortized.
"""

from __future__ import annotations

import jax.numpy as jnp

DENSE_MAX_ELEMS = 1 << 22


def fmix64(x: jnp.ndarray) -> jnp.ndarray:
    """64-bit avalanche mix (MurmurHash3 fmix64, one multiply round) —
    decorrelates power-of-two-strided keys before a power-of-two modulo."""
    x = x.astype(jnp.uint64)
    x ^= x >> 33
    x *= jnp.uint64(0xFF51AFD7ED558CCD)
    x ^= x >> 33
    return x


def onehot(idx: jnp.ndarray, n: int) -> jnp.ndarray:
    """[R, n] bool: oh[r, j] = (idx[r] == j)."""
    return idx[:, None] == jnp.arange(n, dtype=idx.dtype)[None, :]


def sel(oh: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """Dense gather vals[idx]: [R, n] one-hot x [n] -> [R]."""
    return jnp.sum(jnp.where(oh, vals[None, :], 0), axis=1, dtype=vals.dtype)


def binsum(oh: jnp.ndarray, mask: jnp.ndarray, val) -> jnp.ndarray:
    """Dense scatter-add: per-bin sum of val[r] over rows with mask.

    ``oh`` [R, n], ``mask`` [R], ``val`` scalar or [R] -> [n] int64.
    """
    v = jnp.asarray(val, jnp.int64)
    v = jnp.broadcast_to(v.reshape(-1, 1), oh.shape) if v.ndim else \
        jnp.full(oh.shape, v)
    return jnp.sum(jnp.where(oh & mask[:, None], v, 0), axis=0)


def binmax(oh: jnp.ndarray, mask: jnp.ndarray, val: jnp.ndarray,
           init) -> jnp.ndarray:
    """Dense scatter-max: per-bin max of val[r] over rows with mask."""
    return jnp.max(jnp.where(oh & mask[:, None], val[:, None], init), axis=0)


# Above DENSE_MAX_ELEMS callers fall back to real scatters, which XLA:TPU
# dispatches as SEQUENTIAL ops (~150 us each at 1024 tiles — PROFILE.md
# lever 3).  Scatter cost is per OPERATION, not per payload element, so
# several per-field scatters that share one index vector stack into a
# single multi-field scatter: a [F, size] table updated at [:, idx] with a
# [F, R] payload costs ONE dispatch instead of F.

def stacked_max_table(idx: jnp.ndarray, vals: jnp.ndarray, size: int,
                      init) -> jnp.ndarray:
    """[F, size] per-bin max of vals[f, r] over the SHARED idx[r] — one
    scatter for all F fields.  Mask rows by passing ``init`` (the max
    identity) as their value instead of masking the index: the op count
    stays one and masked rows are no-ops."""
    F = vals.shape[0]
    return jnp.full((F, size), init, vals.dtype).at[:, idx].max(vals)


def stacked_set_table(idx: jnp.ndarray, mask: jnp.ndarray,
                      vals: jnp.ndarray, tbl: jnp.ndarray) -> jnp.ndarray:
    """Update tbl[f, idx[r]] = vals[f, r] where mask[r], one scatter for
    all F rows of ``tbl`` ([F, size]).  Callers guarantee at most one
    masked row per index value (e.g. per-slot election winners), so the
    duplicate-index write order XLA leaves unspecified never matters."""
    size = tbl.shape[1]
    return tbl.at[:, jnp.where(mask, idx, size)].set(vals, mode="drop")


# FCFS election helpers — shared by engine/resolve.py's conflict rounds
# and the chain replay's classify kernel (engine/kernels/chain.py), so
# both paths run literally the same election code (round 10 moved them
# here from resolve.py; semantics unchanged).

BIG = jnp.int64(2**62)


def home_fold(line: jnp.ndarray, n: int) -> jnp.ndarray:
    """Line -> home slot in [0, n): round-robin over consecutive lines
    with the bits above the slot index XOR-folded in first — a plain
    ``line % n`` sends every power-of-two-strided per-tile region to
    ONE home, serializing all T cold misses through a single directory
    set's way election (see resolve.home_of_line).  ONE definition:
    resolve.py's home/DRAM-site lookups and the chain classify kernel's
    slice->controller timing legs must never diverge."""
    bits = max(n.bit_length() - 1, 1)
    x = line ^ (line >> bits) ^ (line >> (2 * bits)) ^ (line >> (3 * bits))
    return (x % n).astype(jnp.int32)


def fcfs_keys(active, issue) -> jnp.ndarray:
    """Per-row FCFS key ordered by (issue, tile), unique per row.

    Issue times are rebased to the earliest active row so the key stays
    far below the ``BIG`` empty-slot sentinel at any simulated time
    (skew within one resolve pass is bounded by quantum + max latency,
    nowhere near the 2^40 clip).
    """
    T = issue.shape[0]
    rows = jnp.arange(T)
    issue0 = jnp.min(jnp.where(active, issue, BIG))
    return jnp.clip(issue - issue0, 0, jnp.int64(2**40)) * T + rows


def elect(active, packed, idx, size):
    """Min-FCFS election: the earliest active row per ``idx`` value wins
    (one winner per table slot; a hash collision between two distinct
    keys mapping to one slot only defers the later row).

    Dense [R, size] mask form when it fits; scatter-min table above the
    size cap (large T), where the serialized scatter is amortized anyway.
    """
    R = packed.shape[0]
    if R * size <= DENSE_MAX_ELEMS:
        oh = onehot(idx, size)
        tbl = jnp.min(jnp.where(oh & active[:, None], packed[:, None], BIG),
                      axis=0)
        return active & (sel(oh, tbl) == packed)
    tbl = jnp.full((size,), BIG, dtype=jnp.int64).at[
        jnp.where(active, idx, size)].min(packed, mode="drop")
    return active & (tbl[idx] == packed)


def grouped_rank(group: jnp.ndarray, key: jnp.ndarray,
                 active: jnp.ndarray) -> jnp.ndarray:
    """FCFS rank of each active row within its ``group``, ordered by
    ``key``, as ONE dense [R, R] masked compare-and-sum.

    Deliberately dense: [R, R] bool work is a few MB of fused vector ops
    even at R = 2048, while sort-based ranking lowers to a serialized
    while-loop of dynamic-update-slices on TPU.  Key ties break by row
    index.  Inactive rows get rank 0.
    """
    R = key.shape[0]
    idx = jnp.arange(R, dtype=jnp.int32)
    g = group.astype(jnp.int32)
    before = (g[None, :] == g[:, None]) \
        & ((key[None, :] < key[:, None])
           | ((key[None, :] == key[:, None]) & (idx[None, :] < idx[:, None]))) \
        & active[None, :] & active[:, None]
    return jnp.sum(before, axis=1, dtype=jnp.int32)
