"""Dense one-hot primitives for TPU-friendly indexed access.

TPU lowering rationale (measured on v5e): XLA lowers real gather/scatter
ops over small arrays to a serialized per-index-row loop (~10 us per op
regardless of payload), while dense masked selects/reduces lower to fused
vector ops at HBM bandwidth (<1 us for this engine's array sizes).  Every
hot-path operation indexed by a [T]-shaped vector therefore goes through a
one-hot mask plus a masked reduce (gather) or masked select (scatter).

Dense one-hots are O(rows * bins) memory; callers that bin into large
spaces (the election hash tables) fall back to real scatters above
``DENSE_MAX_ELEMS`` — at those sizes the serialized scatter is amortized.
"""

from __future__ import annotations

import jax.numpy as jnp

DENSE_MAX_ELEMS = 1 << 22


def fmix64(x: jnp.ndarray) -> jnp.ndarray:
    """64-bit avalanche mix (MurmurHash3 fmix64, one multiply round) —
    decorrelates power-of-two-strided keys before a power-of-two modulo."""
    x = x.astype(jnp.uint64)
    x ^= x >> 33
    x *= jnp.uint64(0xFF51AFD7ED558CCD)
    x ^= x >> 33
    return x


def onehot(idx: jnp.ndarray, n: int) -> jnp.ndarray:
    """[R, n] bool: oh[r, j] = (idx[r] == j)."""
    return idx[:, None] == jnp.arange(n, dtype=idx.dtype)[None, :]


def sel(oh: jnp.ndarray, vals: jnp.ndarray) -> jnp.ndarray:
    """Dense gather vals[idx]: [R, n] one-hot x [n] -> [R]."""
    return jnp.sum(jnp.where(oh, vals[None, :], 0), axis=1, dtype=vals.dtype)


def binsum(oh: jnp.ndarray, mask: jnp.ndarray, val) -> jnp.ndarray:
    """Dense scatter-add: per-bin sum of val[r] over rows with mask.

    ``oh`` [R, n], ``mask`` [R], ``val`` scalar or [R] -> [n] int64.
    """
    v = jnp.asarray(val, jnp.int64)
    v = jnp.broadcast_to(v.reshape(-1, 1), oh.shape) if v.ndim else \
        jnp.full(oh.shape, v)
    return jnp.sum(jnp.where(oh & mask[:, None], v, 0), axis=0)


def binmax(oh: jnp.ndarray, mask: jnp.ndarray, val: jnp.ndarray,
           init) -> jnp.ndarray:
    """Dense scatter-max: per-bin max of val[r] over rows with mask."""
    return jnp.max(jnp.where(oh & mask[:, None], val[:, None], init), axis=0)


# Above DENSE_MAX_ELEMS callers fall back to real scatters, which XLA:TPU
# dispatches as SEQUENTIAL ops (~150 us each at 1024 tiles — PROFILE.md
# lever 3).  Scatter cost is per OPERATION, not per payload element, so
# several per-field scatters that share one index vector stack into a
# single multi-field scatter: a [F, size] table updated at [:, idx] with a
# [F, R] payload costs ONE dispatch instead of F.

def stacked_max_table(idx: jnp.ndarray, vals: jnp.ndarray, size: int,
                      init) -> jnp.ndarray:
    """[F, size] per-bin max of vals[f, r] over the SHARED idx[r] — one
    scatter for all F fields.  Mask rows by passing ``init`` (the max
    identity) as their value instead of masking the index: the op count
    stays one and masked rows are no-ops."""
    F = vals.shape[0]
    return jnp.full((F, size), init, vals.dtype).at[:, idx].max(vals)


def stacked_set_table(idx: jnp.ndarray, mask: jnp.ndarray,
                      vals: jnp.ndarray, tbl: jnp.ndarray) -> jnp.ndarray:
    """Update tbl[f, idx[r]] = vals[f, r] where mask[r], one scatter for
    all F rows of ``tbl`` ([F, size]).  Callers guarantee at most one
    masked row per index value (e.g. per-slot election winners), so the
    duplicate-index write order XLA leaves unspecified never matters."""
    size = tbl.shape[1]
    return tbl.at[:, jnp.where(mask, idx, size)].set(vals, mode="drop")
