"""Streaming segmented trace ingest — double-buffered host→device prefetch.

The reference's Pin frontend is a LIVE event source feeding the timing
models (pin/instruction_modeling.cc analysis calls); our rebuild loaded
every trace whole at startup, so trace length was bounded by HBM and
capture-then-simulate was a two-epoch workflow.  This module converts
ingest into a pipelined hot path: the device holds exactly TWO
fixed-capacity trace segments (active + prefetch), the host uploads the
predicted next window while the current megarun executes (ZSim's
bound-weave phasing, applied to the event feed), and device trace memory
is O(segment_events) for any trace length.

Bit-identity contract (the whole design hangs off it):

  * Engine reads stay in GLOBAL event coordinates; the active segment is
    per-row columns [base[r], base[r]+C) and indices rebase at the gather
    (TraceArrays.local_cols).  Bases are capped at max(N-C, 0), so the
    trace-end clamp (min(pos, N-1)) always lands on a REAL resident
    column — every readable index yields the whole-trace value.
  * One quantum step reads at most ``params.ingest_lookahead`` (L) events
    past any cursor (the window cache's refresh gathers its full [T, WC]
    span; cursors are monotone within a step).  The streamed megarun
    (``megarun``) runs quantum steps SPECULATIVELY: after each step it
    evaluates the per-row overrun guard

        (cursor + L > base + C) and (base + C < n_total)

    on the SPECULATIVE state and rolls the whole quantum back when any
    row fires — by cursor monotonicity the guard fires whenever any
    intermediate read COULD have left the segment, so committed quanta
    only ever saw in-segment (= whole-trace) values and the committed
    state sequence equals the whole-trace sequence bit for bit, every
    SimState leaf (ctr_quantum and the sample rings revert with the
    rollback).  The guard must be evaluated on the speculative state:
    the rolled-back state satisfies the headroom invariant by
    construction and would never flag (livelock).
  * A fired guard ends the megarun (the "segment exhausted"
    generalization of the window cache's refresh guard — swaps happen
    only at megarun window boundaries) and returns the overrun mask to
    the host, which swaps: flagged rows whose committed cursor fits the
    PREFETCHED window ([pbase, pbase+C) with L headroom) adopt it via a
    device select; the rest take a synchronous host rebuild at their
    committed cursor (maximum headroom) — counted entirely as ingest
    stall.  Progress: a swap strictly advances each flagged row's base
    whenever at least one quantum committed since the last swap, which
    holds as long as C - L exceeds the largest single-quantum event
    consumption (a quantum runs MANY window rounds, so this is far
    beyond the C >= 2L floor __post_init__ enforces — size segments
    generously; thousands of events, not hundreds).  If a quantum ever
    consumes more than C - L events from a fresh rebuild, the swap
    detects zero base progress and raises loudly instead of
    livelocking.

Validated subset (everything else refuses loudly — params.__post_init__
for params-only combinations, ``validate_streaming`` for trace-dependent
ones): shard_state=replicated (tile_shards > 1 included — the guard and
trace stay replicated, shard-identical), fast_forward=0, one stream per
tile (the ThreadScheduler's seat indirection would decouple rows from
cursors).  Resident shard_state composes later (ROADMAP).
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from graphite_tpu.config import ConfigError
from graphite_tpu.engine.quantum import quantum_step
from graphite_tpu.engine.state import SimState, TraceArrays
from graphite_tpu.engine.vparams import VariantParams, variant_params
from graphite_tpu.events.schema import Trace
from graphite_tpu.events.segments import SegmentPlan
from graphite_tpu.params import SimParams

__all__ = ["StreamingIngest", "validate_streaming", "megarun"]

def validate_streaming(params: SimParams, num_streams: int) -> None:
    """Trace-dependent streaming checks (params-only combinations reject
    in SimParams.__post_init__).  Loud ConfigError, never a quiet
    fallback to the whole-trace program."""
    if params.segment_events <= 0:
        return
    if num_streams > params.num_tiles:
        raise ConfigError(
            f"trace/segment_events: streamed ingest with "
            f"{num_streams} streams > {params.num_tiles} tiles (the "
            f"ThreadScheduler) is not validated — seat rotation "
            f"decouples tile cursors from trace rows; run multi-thread "
            f"traces whole")


# ------------------------------------------------- streamed megarun
# (the replicated quantum program of engine/quantum.megarun, with the
# speculative-step/rollback carry and the overrun mask as a second
# output; engine/quantum.megarun stays byte-identical for whole traces)

def _overrun_guard(params: SimParams, trace: TraceArrays):
    C = trace.addr.shape[1]
    L = params.ingest_lookahead

    def guard(st: SimState) -> jnp.ndarray:
        lim = trace.base + C                       # [T] int32, global
        # Tail segments (covering column n_total-1) are exempt: the
        # global clamp keeps every read in-segment there.  No done/park
        # masking — the window-cache refresh gathers every row, so even
        # a finished row's read span must stay resident.
        return (st.cursor + L > lim) & (lim < trace.n_total)

    return guard


def _streamed_loop(params: SimParams, vp: VariantParams, state: SimState,
                   trace: TraceArrays, max_quanta
                   ) -> Tuple[SimState, jnp.ndarray]:
    guard = _overrun_guard(params, trace)
    start = state.ctr_quantum
    budget = jnp.asarray(max_quanta, jnp.int64)

    def cond(carry):
        st, done, om = carry
        return (~done) & (~om.any()) \
            & ((st.ctr_quantum - start) < budget)

    def body(carry):
        st, _done, _om = carry
        new = quantum_step(params, st, trace, vp=vp)
        nom = guard(new)                 # on the SPECULATIVE state
        over = nom.any()
        # Roll the whole quantum back when any row may have read past
        # its segment — ctr_quantum, counters, and the sample rings all
        # revert with it, so the committed sequence is exactly the
        # whole-trace quantum sequence.
        st = jax.tree_util.tree_map(
            lambda o, n: jnp.where(over, o, n), st, new)
        return st, st.all_done(), nom

    om0 = jnp.zeros(state.cursor.shape[0], dtype=bool)
    state, _, om = jax.lax.while_loop(
        cond, body, (state, state.all_done(), om0))
    return state, om


def _megarun_impl(params: SimParams, state: SimState, trace: TraceArrays,
                  max_quanta) -> Tuple[SimState, jnp.ndarray]:
    from graphite_tpu.parallel.mesh import shard_wrap

    def run(state, trace, vp, mq):
        return _streamed_loop(params, vp, state, trace, mq)

    return shard_wrap(params.tile_shards, run, 4)(
        state, trace, variant_params(params), max_quanta)


# Never donates: the rollback carry aliases old and new state inside the
# loop, and streamed runs redispatch against fresh trace buffers anyway
# (see quantum.state_donation_enabled for the donation hazard history).
_megarun = partial(jax.jit, static_argnums=0)(_megarun_impl)


def megarun(params: SimParams, state: SimState, trace: TraceArrays,
            max_quanta) -> Tuple[SimState, jnp.ndarray]:
    """Streamed megarun: quantum steps on device until done, budget
    exhaustion, or a segment overrun; returns (state, overrun mask).
    A True row in the mask means the megarun stopped at a segment seam
    — swap via StreamingIngest.swap and redispatch."""
    if trace.base is None:
        raise ValueError("streamed megarun needs a segmented TraceArrays "
                         "(StreamingIngest.arrays); whole traces run "
                         "through engine/quantum.megarun")
    return _megarun(params, state, trace, max_quanta)


# --------------------------------------------------- host-side ingest

def _metrics():
    from graphite_tpu.obs.registry import ingest_metrics
    return ingest_metrics()


class StreamingIngest:
    """Double-buffered host→device segment feed for one run.

    Owns the host-resident full trace (engine layout), the device-
    resident active segment (``arrays`` — what the streamed megarun
    reads), one prefetch buffer, and the swap/stall accounting.  Driver
    protocol (engine/sim.Simulator.run):

        dispatch megarun          # async
        ingest.start_prefetch()   # host slice + device_put overlap it
        ... device_get results ...
        if om.any(): trace = ingest.swap(om, cursor)   # the seam
    """

    def __init__(self, params: SimParams, trace: Trace):
        if params.segment_events <= 0:
            raise ValueError("StreamingIngest needs trace/segment_events "
                             "> 0")
        validate_streaming(params, trace.num_tiles)
        self.params = params
        self.plan = SegmentPlan(trace, params.segment_events)
        self.lookahead = params.ingest_lookahead
        # Prefetch prediction stride: half a segment keeps the committed
        # cursor inside BOTH the active and the predicted window around
        # the expected swap point, so steady-state swaps adopt the
        # prefetch instead of hard-rebuilding.
        self.step = max(self.plan.segment_events // 2, 1)
        self.bases = np.zeros(self.plan.num_rows, dtype=np.int32)
        addr, meta = self.plan.slice_rows(self.bases)
        from graphite_tpu.obs import span
        with span("ingest.upload", events=int(addr.size),
                  segment_events=self.plan.segment_events):
            self.arrays = TraceArrays(
                addr=jax.device_put(jnp.asarray(addr)),
                meta=jax.device_put(jnp.asarray(meta)),
                base=jax.device_put(jnp.asarray(self.bases)),
                n_total=int(self.plan.n_total))
        self._prefetch: Optional[Tuple[np.ndarray, jnp.ndarray,
                                       jnp.ndarray]] = None
        # -- accounting (SimSummary/bench surface these)
        self.seams = 0                 # swap events (segment seams hit)
        self.rows_prefetched = 0       # flagged rows served by prefetch
        self.rows_rebuilt = 0          # flagged rows hard-rebuilt
        self.stall_seconds = 0.0       # host time the pipeline blocked
        self.peak_device_trace_bytes = self.plan.segment_bytes() * (
            2 if self.plan.num_segments > 1 else 1)
        self.base_sum = 0              # monotone swap-progress witness
        self._last_swap_prefetched = False
        _metrics()[2].set(self.peak_device_trace_bytes)

    def start_prefetch(self) -> None:
        """Build + upload the predicted next per-row window.  Called
        right after the megarun dispatch: the host slice and the
        device_put overlap the device compute (that overlap IS the
        double buffer)."""
        if self._prefetch is not None or self.plan.num_segments <= 1:
            return
        pb = self.plan.cap_bases(self.bases.astype(np.int64) + self.step)
        if np.array_equal(pb, self.bases):
            return     # every row already holds its tail segment
        from graphite_tpu.obs import span
        addr, meta = self.plan.slice_rows(pb)
        with span("ingest.prefetch", events=int(addr.size)):
            self._prefetch = (pb, jax.device_put(jnp.asarray(addr)),
                              jax.device_put(jnp.asarray(meta)))

    def swap(self, overrun: np.ndarray, cursor: np.ndarray) -> TraceArrays:
        """Serve one segment seam: advance every flagged row's segment
        and return the new active TraceArrays.  The whole call is
        pipeline-blocking, so its wall time is the ingest stall."""
        t0 = time.perf_counter()
        flagged = np.asarray(overrun, dtype=bool)
        cur = np.asarray(cursor, dtype=np.int64)
        if not flagged.any():
            return self.arrays
        from graphite_tpu.obs import span
        with span("ingest.swap", rows=int(flagged.sum())):
            self._swap(flagged, cur)
        dt = time.perf_counter() - t0
        self.stall_seconds += dt
        counter, hist, _gauge = _metrics()
        if self._last_swap_prefetched:
            counter.inc()
        hist.observe(dt)
        return self.arrays

    def _swap(self, flagged: np.ndarray, cur: np.ndarray) -> None:
        C = self.plan.segment_events
        L = self.lookahead
        new_bases = self.bases.astype(np.int64).copy()
        can = np.zeros(self.plan.num_rows, dtype=bool)
        if self._prefetch is not None:
            pb = self._prefetch[0].astype(np.int64)
            can = flagged & (pb <= cur) & (cur + L <= pb + C)
            new_bases[can] = pb[can]
        hard = flagged & ~can
        new_bases[hard] = np.minimum(cur[hard], self.plan.max_base)
        new_bases = self.plan.cap_bases(new_bases)
        if not (new_bases[flagged] > self.bases[flagged]).all():
            # Unreachable given C >= 2L (params.__post_init__): every
            # flagged row's committed cursor strictly exceeds its base.
            raise RuntimeError(
                "streaming ingest made no progress at a segment seam: "
                "a single quantum consumed more than segment_events - "
                "lookahead events, so even a rebuild at the committed "
                "cursor cannot give the next quantum headroom — raise "
                "trace/segment_events (size it several times the "
                "largest single-quantum event consumption)")
        addr, meta = self.arrays.addr, self.arrays.meta
        if can.any():
            # The wait for the in-flight upload is the stall the
            # prefetch overlap exists to hide (near-zero when it kept
            # up with the megarun).
            _, paddr, pmeta = self._prefetch
            paddr.block_until_ready()
            pmeta.block_until_ready()
            cd = jnp.asarray(can)
            addr = jnp.where(cd[:, None], paddr, addr)
            meta = jnp.where(cd[None, :, None], pmeta, meta)
        if hard.any():
            haddr, hmeta = self.plan.slice_rows(new_bases)
            hd = jnp.asarray(hard)
            addr = jnp.where(hd[:, None], jnp.asarray(haddr), addr)
            meta = jnp.where(hd[None, :, None], jnp.asarray(hmeta), meta)
        self.bases = new_bases
        self.arrays = TraceArrays(
            addr=addr, meta=meta, base=jnp.asarray(new_bases),
            n_total=self.arrays.n_total)
        self._prefetch = None          # consumed / stale — rebuilt after
        #   the next dispatch
        self.seams += 1
        self.rows_prefetched += int(can.sum())
        self.rows_rebuilt += int(hard.sum())
        self.base_sum = int(new_bases.sum())
        self._last_swap_prefetched = bool(can.any())

    def rebase(self, bases: np.ndarray) -> None:
        """Re-slice the active segment at explicit per-row bases
        (checkpoint restore).  Bases are capped; any base <= the row's
        cursor resumes bit-identically — placement decides residency,
        never values."""
        self.bases = self.plan.cap_bases(bases)
        addr, meta = self.plan.slice_rows(self.bases)
        self.arrays = TraceArrays(
            addr=jax.device_put(jnp.asarray(addr)),
            meta=jax.device_put(jnp.asarray(meta)),
            base=jax.device_put(jnp.asarray(self.bases)),
            n_total=int(self.plan.n_total))
        self._prefetch = None
        self.base_sum = int(self.bases.astype(np.int64).sum())

    def stall_fraction(self, host_seconds: float) -> float:
        return self.stall_seconds / host_seconds if host_seconds > 0 \
            else 0.0

    def stats(self) -> dict:
        return {
            "segment_events": self.plan.segment_events,
            "num_segments": self.plan.num_segments,
            "seams": self.seams,
            "rows_prefetched": self.rows_prefetched,
            "rows_rebuilt": self.rows_rebuilt,
            "ingest_stall_seconds": round(self.stall_seconds, 6),
            "peak_device_trace_bytes": self.peak_device_trace_bytes,
        }
