"""Pallas TPU round-cost kernels (round 10; ``tpu/pallas_kernels``).

The engine's round COST on TPU is dominated by dispatch: the block
window's K-deep walk and the chain replay's per-iteration table phases
each lower to dozens of small sequential XLA ops (~150 us dispatch each
at T = 1024 — PROFILE.md), while the arithmetic itself is integer work
over arrays that fit VMEM many times over.  This package runs those
phases as FUSED Pallas kernels — the ZSim bound-weave / Sniper
interval-core move: once event ordering is settled, per-event timing
arithmetic should run at memory speed, not dispatch speed.

Layout:
  * ``dispatch.py`` — mode resolution (lax / interpret / tpu), the
    pallas_call plumbing shared by both kernels, and structural-evidence
    helpers (jaxpr op counts) for bench.py / PROFILE.md.
  * ``window.py``   — the block-window walk (engine/core._block_retire's
    hot loop) as a pure per-tile function + its fused kernel wrapper.
  * ``chain.py``    — the chain replay iteration's classify/elect/
    combine/price sub-chain (engine/resolve.chain_fast_pass) + wrapper.

The kernels are NOT reimplementations: each wraps the SAME pure
walk/classify function the lax path calls inline, executed on
block-sliced operands inside one ``pl.pallas_call``.  All arithmetic is
integer and per-tile independent, so kernels-on is bit-identical to
kernels-off by construction — enforced by tests/test_kernels.py.
"""

from graphite_tpu.engine.kernels import dispatch  # noqa: F401
