"""Kernel-mode resolution + shared pallas_call plumbing.

``tpu/pallas_kernels`` selects per-phase execution:

  * ``off``       — the untouched lax path (CPU default: XLA:CPU has no
                    per-op dispatch cost to amortize, and Mosaic cannot
                    lower there anyway).
  * ``interpret`` — ``pl.pallas_call(..., interpret=True)``: the same
                    kernel body evaluated by the Pallas interpreter on
                    any backend.  This is the CPU-testable path the
                    bit-identity gate runs.
  * ``tpu``       — real Mosaic lowering (one custom-call per phase).
  * ``auto``      — ``tpu`` when the default jax backend is TPU, else
                    ``off``.

Phase support is gated here (``window_mode`` / ``chain_mode``): a config
the kernels do not cover (iocoom cores, non-divisible tile blocks) falls
back to lax for that phase — never a behavior change, because the kernel
and lax paths share one walk function and are bit-identical wherever
both run.

The pallas_call plumbing (:func:`call_blocked`) is shape-driven: inputs
and outputs are pytrees whose leaves each declare which axis (if any) is
the tile axis; leaves without one broadcast to every grid step.  Scalars
ride as (1, 1) operands (SMEM-shaped for the TPU path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from graphite_tpu.params import SimParams


def kernels_mode(params: SimParams) -> str:
    """Resolve ``tpu/pallas_kernels`` to 'off' | 'interpret' | 'tpu'."""
    v = params.pallas_kernels
    if v == "auto":
        return "tpu" if jax.default_backend() == "tpu" else "off"
    if v == "on":
        return "tpu"
    return v


def tile_block(num_tiles: int, cap: int = 128) -> int:
    """Tile-block size of the window kernel's grid: the largest
    power-of-two divisor of T up to ``cap`` (T is a power of two in
    every supported mesh, so this is min(T, cap); a non-power-of-two T
    degrades to one block rather than a partial one)."""
    tb = min(num_tiles, cap)
    while tb > 1 and num_tiles % tb:
        tb //= 2
    return max(tb, 1)


def window_mode(params: SimParams) -> str:
    """Kernel mode for the block-window walk; 'off' when the config
    needs lax-only machinery (iocoom drain floors / register-annotated
    windows thread per-tile static masks the blocked kernel does not
    carry)."""
    mode = kernels_mode(params)
    if mode == "off":
        return "off"
    if params.core.model != "simple":
        return "off"
    return mode


def chain_mode(params: SimParams) -> str:
    """Kernel mode for the chain replay's classify phase.  The fast pass
    itself already requires simple cores + full_map + uncontended NoC
    (resolve.chain_fast_pass restrictions), so the kernel inherits those
    gates from its caller."""
    return kernels_mode(params)


def _as_operand(leaf):
    """Scalars become (1, 1) operands (TPU SMEM wants 2-D scalars)."""
    arr = jnp.asarray(leaf)
    if arr.ndim == 0:
        return arr.reshape(1, 1)
    return arr


def _load(ref, was_scalar: bool):
    val = ref[...]
    return val[0, 0] if was_scalar else val


def _block_spec(pl, shape, tile_axis, tb):
    if tile_axis is None or shape == ():
        blk = tuple(shape) if shape else (1, 1)
        nd = len(blk)
        return pl.BlockSpec(blk, lambda i, _nd=nd: (0,) * _nd)
    blk = tuple(tb if a == tile_axis else shape[a]
                for a in range(len(shape)))
    ta = tile_axis

    def imap(i, _ta=ta, _nd=len(blk)):
        return tuple(i if a == _ta else 0 for a in range(_nd))

    return pl.BlockSpec(blk, imap)


def call_blocked(fn, in_tree, in_axes, out_tree_shapes, out_axes,
                 num_tiles: int, mode: str, name: str):
    """Run ``fn(in_tree) -> out_tree`` as ONE pallas_call gridded over
    tile blocks.

    ``in_tree`` / ``out_tree_shapes``: pytrees of arrays / of
    ShapeDtypeStructs (from ``jax.eval_shape`` on the lax path, so the
    kernel's output contract is the walk function's, by construction).
    ``in_axes`` / ``out_axes``: matching pytrees of tile-axis ints (or
    None for broadcast leaves).  ``fn`` must be per-tile independent
    along those axes — the walk/classify functions are, by design.
    """
    from jax.experimental import pallas as pl

    in_leaves, treedef = jax.tree_util.tree_flatten(in_tree)
    ax_leaves = jax.tree_util.tree_leaves(
        in_axes, is_leaf=lambda x: x is None)
    assert len(ax_leaves) == len(in_leaves), (name, len(ax_leaves),
                                              len(in_leaves))
    out_leaves, out_treedef = jax.tree_util.tree_flatten(out_tree_shapes)
    oax_leaves = jax.tree_util.tree_leaves(
        out_axes, is_leaf=lambda x: x is None)
    assert len(oax_leaves) == len(out_leaves)

    tb = tile_block(num_tiles)
    grid = (num_tiles // tb,)

    # Trace the walk ONCE to a closed jaxpr AT BLOCK SHAPES (tile axes
    # sliced to tb — the shapes the kernel body actually sees; the walk
    # functions are shape-polymorphic over the tile axis, and every
    # shape-derived constant they mint — iotas, zero masks — is then
    # block-sized and identical for every grid step).  The jaxpr's
    # constants become extra broadcast operands — pallas_call kernels
    # may not close over consts — and the kernel body replays the jaxpr
    # on the loaded blocks.
    def _block_aval(leaf, ax):
        shape = tuple(jnp.shape(leaf))
        if ax is not None:
            shape = tuple(tb if a == ax else shape[a]
                          for a in range(len(shape)))
        return jax.ShapeDtypeStruct(shape, jnp.asarray(leaf).dtype)

    block_avals = jax.tree_util.tree_unflatten(
        treedef, [_block_aval(leaf, ax)
                  for leaf, ax in zip(in_leaves, ax_leaves)])
    closed = jax.make_jaxpr(lambda t: fn(t))(block_avals)
    consts = list(closed.consts)
    n_in = len(in_leaves)
    n_const = len(consts)
    all_in = consts + in_leaves
    all_axes = [None] * n_const + list(ax_leaves)
    scalars = [jnp.ndim(leaf) == 0 for leaf in all_in]
    operands = [_as_operand(leaf) for leaf in all_in]
    in_specs = [_block_spec(pl, op.shape, ax, tb)
                for op, ax in zip(operands, all_axes)]
    out_specs = [_block_spec(pl, tuple(o.shape), ax, tb)
                 for o, ax in zip(out_leaves, oax_leaves)]
    out_shape = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype)
                 for o in out_leaves]

    def kernel(*refs):
        ins = refs[:n_const + n_in]
        outs = refs[n_const + n_in:]
        loaded = [_load(r, sc) for r, sc in zip(ins, scalars)]
        res_leaves = jax.core.eval_jaxpr(
            closed.jaxpr, loaded[:n_const], *loaded[n_const:])
        assert len(res_leaves) == len(outs)
        for ref, val in zip(outs, res_leaves):
            ref[...] = val

    call = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape, interpret=(mode == "interpret"),
        name=name)
    flat_out = call(*operands)
    if not isinstance(flat_out, (list, tuple)):
        flat_out = [flat_out]
    return jax.tree_util.tree_unflatten(out_treedef, list(flat_out))


def pack(nt, axes_table: dict, vp):
    """NamedTuple of operands (+ the VariantParams pytree) -> (dict of
    present leaves, dict of tile axes, vp treedef).  Dict trees flatten
    by sorted key, so operand and axis leaves stay aligned through
    pallas_call; None fields (machinery compiled out of this config)
    simply vanish."""
    d = {f: v for f, v in zip(type(nt)._fields, nt) if v is not None}
    axes = {f: axes_table[f] for f in d}
    vleaves, vdef = jax.tree_util.tree_flatten(vp)
    for i, leaf in enumerate(vleaves):
        d[f"zvp{i:03d}"] = leaf
        axes[f"zvp{i:03d}"] = None
    return d, axes, vdef


def unpack(cls, d: dict, vdef):
    """Inverse of :func:`pack` inside the kernel body."""
    nv = sum(1 for k in d if k.startswith("zvp"))
    vp = jax.tree_util.tree_unflatten(
        vdef, [d[f"zvp{i:03d}"] for i in range(nv)])
    nt = cls(**{f: d.get(f) for f in cls._fields})
    return nt, vp


def run_fused(core_fn, nt, vp, in_axes: dict, out_cls, out_axes: dict,
              grid_tiles: int, mode: str, name: str):
    """Run ``core_fn(operands, vp) -> out_cls(...)`` as one fused
    pallas_call (interpret or tpu).  ``grid_tiles`` is the tile count
    the in/out axes are blocked over (1 => a single whole-array grid
    step, the chain kernel's shape)."""
    d, axes, vdef = pack(nt, in_axes, vp)
    cls = type(nt)

    def fn(dd):
        nt2, vp2 = unpack(cls, dd, vdef)
        out = core_fn(nt2, vp2)
        return {f: v for f, v in zip(out_cls._fields, out)
                if v is not None}

    out_shapes = jax.eval_shape(fn, d)
    oaxes = {f: out_axes[f] for f in out_shapes}
    od = call_blocked(fn, d, axes, out_shapes, oaxes, grid_tiles, mode,
                      name)
    return out_cls(**{f: od.get(f) for f in out_cls._fields})


# ------------------------------------------------- structural evidence

def jaxpr_op_counts(fn, *args) -> dict:
    """Count the op classes the round-cost story is about in ``fn``'s
    closed jaxpr (recursively through scan/while/cond/pjit bodies):
    total equations, gathers, scatters, and pallas_call sites.  This is
    the CPU-checkable form of the "window phase collapses to one
    custom-call" claim — each pallas_call eqn lowers to exactly one TPU
    custom-call by construction."""
    closed = jax.make_jaxpr(fn)(*args)
    counts = {"eqns": 0, "gather": 0, "scatter": 0, "pallas_call": 0,
              "while": 0, "fori_or_scan": 0, "collective": 0}

    # Cross-device communication primitives (startswith, to catch the
    # psum/psum2 and reduce_scatter naming variants across jax versions).
    # The round-11 sharding gates assert the shard-LOCAL window phase has
    # zero of these and the whole sharded step a small bounded count.
    # Each family is ALSO counted under its own key (zero-initialized so
    # absent families read 0): the round-15 resident gate asserts the
    # per-family budget — zero all_gathers, a bounded all_to_all count,
    # exactly one pmin — not just the total.
    _COLLECTIVES = ("all_gather", "psum", "pmin", "pmax", "all_to_all",
                    "ppermute", "reduce_scatter", "pbroadcast")
    for fam in _COLLECTIVES:
        counts[fam] = 0

    def visit(jaxpr):
        for eqn in jaxpr.eqns:
            counts["eqns"] += 1
            prim = eqn.primitive.name
            if prim == "gather":
                counts["gather"] += 1
            elif prim.startswith("scatter"):
                counts["scatter"] += 1
            elif prim == "pallas_call":
                counts["pallas_call"] += 1
            elif prim == "while":
                counts["while"] += 1
            elif prim == "scan":
                counts["fori_or_scan"] += 1
            if prim.startswith(_COLLECTIVES):
                counts["collective"] += 1
                for fam in _COLLECTIVES:
                    if prim.startswith(fam):
                        counts[fam] += 1
                        break
            # Recurse into sub-jaxprs (loop/cond/pjit bodies ride in
            # eqn params) — pallas_call kernel jaxprs are deliberately
            # NOT descended into: their ops are fused inside one call.
            if prim != "pallas_call":
                for v in eqn.params.values():
                    for sub in _subjaxprs_of(v):
                        visit(sub)

    def _subjaxprs_of(v):
        if isinstance(v, jax.core.ClosedJaxpr):
            return [v.jaxpr]
        if isinstance(v, jax.core.Jaxpr):
            return [v]
        if isinstance(v, (list, tuple)):
            out = []
            for item in v:
                out.extend(_subjaxprs_of(item))
            return out
        return []

    visit(closed.jaxpr)
    return counts
