"""The block-window walk as a pure per-tile function + its fused kernel.

``window_walk`` is engine/core._block_retire's hot loop — tag probes
against every cache level, hit/stall/hazard classification over the
[T, K] window, within-window branch-predictor RAW, the max-plus clock
prefix, chain banking, LRU touch / fill application, and counter
accumulation — extracted so ONE implementation serves both execution
paths:

  * the lax path calls it inline on full [T, ...] operands (the program
    is op-for-op the pre-round-10 engine);
  * the Pallas path (``run_window`` with mode 'interpret' / 'tpu') runs
    the SAME function inside ``pl.pallas_call``, gridded over tile
    blocks, so the K-deep walk's dozens of gathers, [T, K, K] mask
    reductions, and scatter applies fuse into one kernel (one TPU
    custom-call) over VMEM-resident operands.

Every value in the walk is integer and per-tile independent (the only
cross-tile effect of the window phase — the SPAWN landing scatter — is
returned as (mask, child, time) triples and applied by the caller), so
block-slicing the tile axis is exact and kernels-on is bit-identical to
kernels-off by construction.  tests/test_kernels.py enforces it.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from graphite_tpu.engine import cache as cachemod
from graphite_tpu.engine import dense
from graphite_tpu.engine import noc
from graphite_tpu.engine.kernels import dispatch
from graphite_tpu.engine.state import PEND_EX_REQ, PEND_IFETCH, PEND_SH_REQ
from graphite_tpu.engine.vparams import VariantParams
from graphite_tpu.events.schema import ICACHE_BYTES_PER_INSTRUCTION
from graphite_tpu.isa import DVFSModule, EventOp
from graphite_tpu.params import SimParams

I, S, E, M = cachemod.I, cachemod.S, cachemod.E, cachemod.M


def _lat(cycles, period_ps):
    """cycles (int/array) at an integer ps clock period -> int64 ps."""
    return jnp.asarray(cycles, jnp.int64) * jnp.asarray(period_ps, jnp.int64)


class WindowIn(NamedTuple):
    """Window-walk operands.  Tile-axis positions in WINDOW_IN_AXES;
    fields whose machinery is compiled out of this config are None."""

    meta: jnp.ndarray           # [3, T, K] int32 (op, arg, arg2)
    addr: jnp.ndarray           # [T, K] int64
    valid_ev: jnp.ndarray       # [T, K] bool (pos < N & tile_active)
    tile_active: jnp.ndarray    # [T] bool
    tile_ids: jnp.ndarray       # [T] int32 GLOBAL tile index (spawn src)
    clock: jnp.ndarray          # [T] int64
    period_ps: jnp.ndarray      # [T, NUM_DVFS_MODULES] int32
    bp_table: jnp.ndarray       # [T, bp_size] bool
    l1i_word: jnp.ndarray       # [A, T, sets] int64
    l1i_rr: jnp.ndarray         # [T, sets] int32
    l1d_word: jnp.ndarray
    l1d_rr: jnp.ndarray
    l2_word: Optional[jnp.ndarray]   # None under shared L2
    l2_rr: Optional[jnp.ndarray]
    boundary: jnp.ndarray       # [] int64
    models_enabled: jnp.ndarray  # [] bool
    stamp_base: jnp.ndarray     # [] int32 (round_ctr * STAMP_STRIDE)
    # Miss-chain state (None at P == 0).
    chain_rel: Optional[jnp.ndarray]  # [T] int64
    mq_count: Optional[jnp.ndarray]   # [T] int32
    mq_head: Optional[jnp.ndarray]    # [T] int32
    mq_req: Optional[jnp.ndarray]     # [P, T] int64
    mq_delta: Optional[jnp.ndarray]   # [P, T] int64
    mq_extra: Optional[jnp.ndarray]   # [P, T] int64
    # iocoom rings (None for simple cores; lax path only).
    lq_ready: Optional[jnp.ndarray]   # [LQE, T] int64
    sq_ready: Optional[jnp.ndarray]   # [SQE, T] int64


WINDOW_IN_AXES = dict(
    meta=1, addr=0, valid_ev=0, tile_active=0, tile_ids=0, clock=0,
    period_ps=0, bp_table=0, l1i_word=1, l1i_rr=0, l1d_word=1, l1d_rr=0,
    l2_word=1, l2_rr=0, boundary=None, models_enabled=None,
    stamp_base=None, chain_rel=0, mq_count=0, mq_head=0, mq_req=1,
    mq_delta=1, mq_extra=1, lq_ready=1, sq_ready=1,
)


# Counter increments, in the order ``ctr_inc`` rows are stacked.
WINDOW_CTRS = (
    "icount", "l1i_access", "l1i_miss", "l1d_read", "l1d_read_miss",
    "l1d_write", "l1d_write_miss", "l2_access", "l2_miss", "branches",
    "mispredicts", "spawns",
)


class WindowOut(NamedTuple):
    clock: jnp.ndarray          # [T] int64
    n_ret: jnp.ndarray          # [T] int32 events retired (cursor inc)
    bp_table: jnp.ndarray       # [T, bp_size] bool
    l1i_word: jnp.ndarray
    l1i_rr: jnp.ndarray
    l1d_word: jnp.ndarray
    l1d_rr: jnp.ndarray
    l2_word: Optional[jnp.ndarray]
    l2_rr: Optional[jnp.ndarray]
    ctr_inc: jnp.ndarray        # [len(WINDOW_CTRS), T] int64
    spawn_mask: jnp.ndarray     # [T, K] bool (is_spawn & retired)
    spawn_child: jnp.ndarray    # [T, K] int32 clipped stream id
    spawn_land: jnp.ndarray     # [T, K] int64 landing time
    chain_rel: Optional[jnp.ndarray]
    mq_count: Optional[jnp.ndarray]
    mq_req: Optional[jnp.ndarray]
    mq_delta: Optional[jnp.ndarray]
    mq_extra: Optional[jnp.ndarray]


WINDOW_OUT_AXES = dict(
    clock=0, n_ret=0, bp_table=0, l1i_word=1, l1i_rr=0, l1d_word=1,
    l1d_rr=0, l2_word=1, l2_rr=0, ctr_inc=1, spawn_mask=0, spawn_child=0,
    spawn_land=0, chain_rel=0, mq_count=0, mq_req=1, mq_delta=1,
    mq_extra=1,
)


def _spanned_bound(params: SimParams, vp, boundary):
    """Round-9 boundary-spanning bound (``tpu/fanout_replay``, effective
    only at miss_chain > 0): the window, complex-slot, and cadence gates
    all admit ONE QUANTUM of overrun past the cut — the same allowance
    mid-chain tiles already get via ``rel < qps``, the same skew class
    the lax model absorbs (the 2% chain-oracle gate bounds it).  Strict
    at miss_chain == 0 (that engine is the bit-identity oracle) and with
    the replay off (the round-8 cadence).  The ONE definition — core.py
    aliases it, so the walk and the complex-slot/cadence gates can never
    drift apart."""
    if params.miss_chain > 0 and params.fanout_replay:
        q = vp.quantum_ps if vp is not None \
            else jnp.int64(params.quantum_ps)
        return boundary + q
    return boundary


def _ff_bound(params: SimParams, vp, boundary):
    """Round-12 fast-forward bound: the analytic span commits events
    whose pre-clock stays under the same (possibly quantum-spanned)
    bound the window's per-event prefix enforces, PLUS the VARIANT
    run-ahead budget ``tpu/fast_forward_span`` — Graphite's lax-sync
    trade scoped to the closed-form leg.  At span 0 the bound equals
    the window's exactly, so fast-forwarded tiles stop where detailed
    rounds would.  ONE definition (core.py aliases it) so the cadence
    gate and the walk's commit mask can never drift apart."""
    b = _spanned_bound(params, vp, boundary)
    if params.fast_forward > 0:
        span = vp.fast_forward_span_ps if vp is not None \
            else jnp.int64(params.fast_forward_span_ps)
        return b + span
    return b


def window_walk(params: SimParams, vp: VariantParams, wi: WindowIn,
                s_ids: int) -> WindowOut:
    """Classify + retire one [TL, K] window (TL = full T on the lax
    path, one tile block inside the kernel).  Pure: reads only ``wi``,
    returns every effect.  The body is engine/core._block_retire's walk,
    verbatim apart from the input plumbing — see that docstring for the
    semantics commentary.

    Width-polymorphic like the tile axis: K is the EVENT axis of the
    operands, normally ``params.block_events`` but ``core._ff_width``
    events for a round-12 wide fast-forward round (``tpu/fast_forward``
    > 0) — the same walk, probing/banking/hazarding over a longer
    window, so the wide rounds can never drift from the narrow ones."""
    K = wi.addr.shape[1]
    TL = wi.clock.shape[0]               # LOCAL tile count (block size)
    P = params.miss_chain
    line_bits = params.line_size.bit_length() - 1
    rows = jnp.arange(TL)
    shared_l2 = params.shared_l2
    mesi_local = params.protocol_kind == "sh_l2_mesi"
    iocoom = params.core.model == "iocoom"

    l1i = cachemod.CacheArrays(word=wi.l1i_word, rr_ptr=wi.l1i_rr)
    l1d = cachemod.CacheArrays(word=wi.l1d_word, rr_ptr=wi.l1d_rr)
    l2 = None if shared_l2 else cachemod.CacheArrays(word=wi.l2_word,
                                                     rr_ptr=wi.l2_rr)

    nm0 = wi.mq_count if P > 0 else jnp.zeros(TL, dtype=jnp.int32)
    wbound = _spanned_bound(params, vp, wi.boundary)
    tile_active = wi.tile_active
    valid_ev = wi.valid_ev
    meta, addr = wi.meta, wi.addr
    op, arg, arg2 = meta[0], meta[1], meta[2]
    op = jnp.where(valid_ev, op, EventOp.NOP)

    en = wi.models_enabled            # scalar bool (flips are complex ops)

    # ---- per-tile clock periods (DVFS-aware), ps per cycle
    p_core = wi.period_ps[:, int(DVFSModule.CORE)][:, None]
    p_l1i = wi.period_ps[:, int(DVFSModule.L1_ICACHE)][:, None]
    p_l1d = wi.period_ps[:, int(DVFSModule.L1_DCACHE)][:, None]
    p_l2 = wi.period_ps[:, int(DVFSModule.L2_CACHE)][:, None]
    l1i_ps = _lat(vp.l1i_access_cycles, p_l1i)
    l1d_ps = _lat(vp.l1d_access_cycles, p_l1d)
    l2_ps = _lat(vp.l2_access_cycles, p_l2)
    cycle_ps = _lat(1, p_core)

    line = addr >> line_bits
    is_comp = op == EventOp.COMPUTE
    is_br = op == EventOp.BRANCH
    is_rd = op == EventOp.MEM_READ
    is_wr = op == EventOp.MEM_WRITE          # atomics stay complex
    is_mem = is_rd | is_wr
    is_stall = op == EventOp.STALL
    is_sync = op == EventOp.SYNC
    is_spawn = op == EventOp.SPAWN

    # ---- probes against window-start state ([TL, K] block gathers)
    pI = cachemod.probe(l1i, line, params.l1i.num_sets)
    pD = cachemod.probe(l1d, line, params.l1d.num_sets)
    if not shared_l2:
        pL2 = cachemod.probe(l2, line, params.l2.num_sets)

    writable = pD.state >= (E if mesi_local else M)
    l1_ok = pD.hit & (is_rd | writable)
    if shared_l2:
        mem_l2 = jnp.zeros_like(l1_ok)
        comp_l2 = jnp.zeros_like(l1_ok)
    else:
        mem_l2 = is_mem & ~l1_ok & pL2.hit & (is_rd | (pL2.state == M))
        comp_l2 = is_comp & ~pI.hit & pL2.hit
    mem_simple = is_mem & (l1_ok | mem_l2)
    comp_simple = is_comp & (pI.hit | comp_l2)
    if iocoom:
        # Register-annotated events need the complex slot's RAW floors —
        # decline them here (see core.py).  Lax path only: the kernel
        # dispatch gates iocoom out.
        annotated = (is_comp & ((arg2 >> 20) != 0)) \
            | (is_rd & (((arg2 >> 8) & 31) != 0))
        if params.core.mixed:
            annotated = annotated \
                & jnp.asarray(params.core.iocoom_mask)[:, None]
        mem_simple = mem_simple & ~annotated
        comp_simple = comp_simple & ~annotated
    fill_d = mem_l2                           # L1D fill from local L2 hit
    fill_i = comp_l2                          # L1I fill from local L2 hit

    # Bankable misses — see core.py for the blocking-semantics notes.
    if P > 0:
        mem_bank0 = is_mem & ~l1_ok & ~mem_l2
        comp_bank0 = is_comp & ~pI.hit & ~comp_l2
    else:
        mem_bank0 = jnp.zeros_like(l1_ok)
        comp_bank0 = jnp.zeros_like(l1_ok)

    if iocoom:
        drain_t = jnp.maximum(jnp.max(wi.lq_ready, axis=0),
                              jnp.max(wi.sq_ready, axis=0))[:, None]
        drain_ev = is_spawn | is_sync \
            | (is_br if not params.core.speculative_loads
               else jnp.zeros_like(is_br))
        if params.core.mixed:
            drain_ev = drain_ev \
                & jnp.asarray(params.core.iocoom_mask)[:, None]
    else:
        drain_ev = jnp.zeros_like(is_br)

    ar = jnp.arange(K)
    earlier = ar[None, :, None] > ar[None, None, :]           # [1, K, K]

    # ---- chain forwarding (hit-on-pending-fill) — core.py notes.
    wfwd = P > 0 and params.fanout_replay
    if P > 0:
        same_line_w = line[:, :, None] == line[:, None, :]    # [T, Kj, Ki]
        fwd_win_d = (earlier & same_line_w & mem_bank0[:, None, :]
                     & is_rd[:, :, None]).any(axis=2)
        fwd_win_i = (earlier & same_line_w
                     & comp_bank0[:, None, :]).any(axis=2)
        # Pending elements banked in earlier rounds ([P, T] chain state).
        slots_pc = jnp.arange(P, dtype=jnp.int32)[:, None]    # [P, 1]
        pvalid = (slots_pc >= wi.mq_head[None, :]) \
            & (slots_pc < wi.mq_count[None, :])               # [P, T]
        pline = wi.mq_req >> 8
        pkind = (wi.mq_req & 7).astype(jnp.int32)
        p_is_if = pkind == PEND_IFETCH
        pend_memT = (pvalid & ~p_is_if).T[:, None, :]         # [T, 1, P]
        pend_ifT = (pvalid & p_is_if).T[:, None, :]
        linematch_p = line[:, :, None] == pline.T[:, None, :]  # [T, K, P]
        cover_pd = linematch_p & pend_memT & is_rd[:, :, None]
        cover_pi = linematch_p & pend_ifT
        if wfwd:
            # Round-9 in-window write-over-EX-bank forwarding.
            fwd_win_w = (earlier & same_line_w
                         & (mem_bank0 & is_wr)[:, None, :]
                         & is_wr[:, :, None]).any(axis=2)
            fwd_win_d = fwd_win_d | fwd_win_w
        fwd_pend_d = jnp.any(cover_pd, axis=2)
        fwd_pend_i = jnp.any(cover_pi, axis=2)
        mem_fwd = mem_bank0 & (fwd_win_d | fwd_pend_d)
        comp_fwd = comp_bank0 & (fwd_win_i | fwd_pend_i)
    else:
        mem_fwd = comp_fwd = jnp.zeros_like(l1_ok)
    mem_bank = mem_bank0 & ~mem_fwd
    comp_bank = comp_bank0 & ~comp_fwd
    mem_simple = mem_simple | mem_fwd
    comp_simple = comp_simple | comp_fwd
    fill_bank_d = mem_bank                    # future L1D fill (hazards)
    fill_bank_i = comp_bank                   # future L1I fill

    # ---- fill hazards (see core.py for the staleness rules)

    def _hazard(fills, accesses, set_idx):
        """accesses[j] unsafe if exists i<j with fills[i] & same set."""
        same = set_idx[:, :, None] == set_idx[:, None, :]     # [T, Kj, Ki]
        return accesses & (earlier & same & fills[:, None, :]).any(axis=2)

    touch_d = is_mem & l1_ok
    touch_i = is_comp & pI.hit
    upg_d = touch_d & is_wr & (pD.state == E) if mesi_local \
        else jnp.zeros_like(touch_d)
    haz_d = _hazard(fill_d | upg_d, is_mem, pD.set_idx) \
        | _hazard(touch_d | fill_d, fill_d, pD.set_idx)
    haz_i = _hazard(fill_i, is_comp, pI.set_idx) \
        | _hazard(touch_i | fill_i, fill_i, pI.set_idx)
    if P > 0 and shared_l2:
        ssD = pD.set_idx[:, :, None] == pD.set_idx[:, None, :]
        haz_d = haz_d | (is_mem & (
            earlier & ssD & ~same_line_w
            & fill_bank_d[:, None, :]).any(axis=2))
        ssI = pI.set_idx[:, :, None] == pI.set_idx[:, None, :]
        haz_i = haz_i | (is_comp & (
            earlier & ssI & ~same_line_w
            & fill_bank_i[:, None, :]).any(axis=2))
    if P > 0:
        bank_w_uncov = (mem_bank0 & ~is_wr) if wfwd else mem_bank0
        uncov_w = earlier & same_line_w & (
            (is_mem[:, :, None] & comp_bank0[:, None, :])
            | (is_wr[:, :, None] & bank_w_uncov[:, None, :])
            | (is_comp[:, :, None] & mem_bank0[:, None, :]))
        hazard_uncov = uncov_w.any(axis=2)
        haz_d = haz_d | (is_mem & hazard_uncov)
        haz_i = haz_i | (is_comp & hazard_uncov)
    hazard = haz_d | haz_i

    # Banked-miss L2 hazards (private) — core.py notes.
    l2_fill_cand = mem_bank | comp_bank
    if P > 0 and not shared_l2:
        l2ss = pL2.set_idx[:, :, None] == pL2.set_idx[:, None, :]
        l2_cover = same_line_w & (
            (is_mem[:, :, None] & mem_bank0[:, None, :]
             & is_rd[:, :, None])
            | (is_comp[:, :, None] & comp_bank0[:, None, :]))
        if wfwd:
            l2_cover = l2_cover | (
                same_line_w & is_wr[:, :, None]
                & (mem_bank0 & is_wr)[:, None, :])
        hazard = hazard | ((is_mem | is_comp) & (
            earlier & l2ss & ~l2_cover
            & l2_fill_cand[:, None, :]).any(axis=2))

    # Pending-chain hazards (stall-on-use across rounds) — core.py.
    if P > 0:
        pvT0 = pvalid.T[:, None, :]
        haz_pend = (is_mem & jnp.any(
            linematch_p & pvT0 & ~cover_pd, axis=2)) \
            | (is_comp & jnp.any(
                linematch_p & pvT0 & ~cover_pi, axis=2))
        if shared_l2:
            pd_set = cachemod.set_index(pline, params.l1d.num_sets).T
            pi_set = cachemod.set_index(pline, params.l1i.num_sets).T
            haz_pend = haz_pend | (is_mem & jnp.any(
                pend_memT & ~cover_pd
                & (pD.set_idx[:, :, None] == pd_set[:, None, :]), axis=2)) \
                | (is_comp & jnp.any(
                    pend_ifT & ~cover_pi
                    & (pI.set_idx[:, :, None] == pi_set[:, None, :]),
                    axis=2))
        else:
            p2_set = cachemod.set_index(pline, params.l2.num_sets).T
            pvT = pvalid.T[:, None, :]
            haz_pend = haz_pend | ((is_mem | is_comp) & jnp.any(
                pvT & ~(cover_pd | cover_pi)
                & (pL2.set_idx[:, :, None] == p2_set[:, None, :]),
                axis=2))
        hazard = hazard | haz_pend

    # Retire classes — core.py notes.
    br_abs = iocoom and not params.core.speculative_loads
    if br_abs and params.core.mixed:
        _iot_w = jnp.asarray(params.core.iocoom_mask)[:, None]
        br_rel = is_br & ~_iot_w
        br_drain = is_br & _iot_w
    elif br_abs:
        br_rel = jnp.zeros_like(is_br)
        br_drain = is_br
    else:
        br_rel = is_br
        br_drain = jnp.zeros_like(is_br)
    base_ok = valid_ev & ~hazard & en
    ok_rel = (comp_simple | mem_simple | br_rel) & base_ok
    ok_abs = (is_stall | is_sync | is_spawn | br_drain) & base_ok
    ok_bank = (mem_bank | comp_bank) & base_ok
    ok = ok_rel | ok_abs | ok_bank            # retire-capable (BP masking)

    # ---- branch predictor: within-window read-after-write on table slots
    if params.core.bp_type == "none":
        correct = jnp.ones_like(is_br)
        bidx = None
    else:
        bidx = (addr % params.core.bp_size).astype(jnp.int32)
        tbl_pred = jnp.take_along_axis(wi.bp_table, bidx, axis=1)
        same_slot = bidx[:, :, None] == bidx[:, None, :]      # [T, Kj, Ki]
        taken = arg != 0
        w_mask = earlier & same_slot & (is_br & ok)[:, None, :]  # [T,Kj,Ki]
        has_w = w_mask.any(axis=2)
        last_w = jnp.argmax(
            jnp.where(w_mask, ar[None, None, :], -1), axis=2)
        pred_blk = jnp.take_along_axis(taken, last_w, axis=1)
        pred = jnp.where(has_w, pred_blk, tbl_pred)
        correct = pred == taken

    # ---- per-event dt (int64 ps) and clock floors
    icount_ev = jnp.maximum(arg2 & ((1 << 20) - 1), 0).astype(jnp.int64)
    n_lines = jnp.maximum(
        (icount_ev * ICACHE_BYTES_PER_INSTRUCTION + params.line_size - 1)
        // params.line_size, 1)
    cost_ps = _lat(jnp.maximum(arg, 0), p_core)
    fetch_ps = icount_ev * l1i_ps
    dt_comp = cost_ps + fetch_ps \
        + jnp.where(comp_l2, n_lines * l2_ps, 0)
    dt_br = jnp.where(correct, cycle_ps,
                      _lat(vp.bp_mispredict_penalty, p_core)) \
        + l1i_ps
    dt_mem = jnp.where(mem_l2, l1d_ps + l2_ps, l1d_ps)
    dt_spawn = _lat(jnp.maximum(arg, 0), p_core)
    dt = jnp.zeros((TL, K), dtype=jnp.int64)
    dt = jnp.where(is_comp, dt_comp, dt)
    dt = jnp.where(is_br, dt_br, dt)
    dt = jnp.where(is_mem, dt_mem, dt)
    dt = jnp.where(is_sync, cost_ps, dt)
    dt = jnp.where(en, dt, jnp.where(is_sync, cost_ps, 0))
    dt = jnp.where(is_spawn, dt_spawn, dt)
    NEGF = jnp.int64(-(2**62))
    floor = jnp.where(is_stall | is_sync, addr, NEGF)
    if iocoom:
        floor = jnp.where(drain_ev, jnp.maximum(floor, drain_t), floor)

    # ---- max-plus prefix (see core.py for the chain-banking notes)
    qps = vp.quantum_ps
    miss_tags_ps = cycle_ps if shared_l2 else \
        _lat(vp.l2_tags_access_cycles, p_l2)
    issue_off = jnp.where(is_comp, l1i_ps, l1d_ps) + miss_tags_ps
    clk = wi.clock
    rel = wi.chain_rel if P > 0 else jnp.zeros(TL, dtype=jnp.int64)
    nm = nm0
    n_ret = jnp.zeros(TL, dtype=jnp.int32)
    run = tile_active
    clks = []
    bank_marks, bank_slots, bank_deltas = [], [], []
    for j in range(K):
        clks.append(clk)                     # clock BEFORE event j
        if P > 0:
            bank_j = ok_bank[:, j] & (nm < P)
            okj = ok_rel[:, j] | (ok_abs[:, j] & (nm == 0)) | bank_j
            in_b = jnp.where(nm == 0, clk < wbound,
                             (rel < qps) & (nm < P))
        else:
            bank_j = jnp.zeros(TL, dtype=bool)
            okj = ok_rel[:, j] | ok_abs[:, j]
            in_b = clk < wi.boundary
        can = run & okj & in_b
        bankc = can & bank_j
        if P > 0:
            bank_marks.append(bankc)
            bank_slots.append(nm)
            bank_deltas.append(
                jnp.where(nm == 0, clk, rel) + issue_off[:, j])
            abs_step = can & (nm == 0) & ~bankc
            rel_step = can & (nm > 0) & ~bankc
            rel = jnp.where(bankc, 0,
                            jnp.where(rel_step, rel + dt[:, j], rel))
            nm = nm + bankc.astype(jnp.int32)
        else:
            abs_step = can
        clk = jnp.where(abs_step,
                        jnp.maximum(clk, floor[:, j]) + dt[:, j], clk)
        n_ret = n_ret + can.astype(jnp.int32)
        run = can
    clk_before = jnp.stack(clks, axis=1)                      # [T, K]
    retired = ar[None, :] < n_ret[:, None]                    # [T, K]

    # ---- SPAWN landing times (the cross-tile scatter itself is the
    # caller's: spawned_at.at[child].max(spawn_land) over these masks).
    child = jnp.clip(arg2, 0, s_ids - 1)
    spawn_base = jnp.maximum(clk_before, floor) if iocoom else clk_before
    spawn_land = spawn_base + dt_spawn + noc.unicast_ps(
        params.net_user,
        jnp.broadcast_to(wi.tile_ids[:, None], (TL, K)),
        child % params.num_tiles, 8,
        wi.period_ps[:, int(DVFSModule.NETWORK_USER)][:, None],
        params.mesh_width, vnet=vp.net_user)
    spawn_mask = is_spawn & retired

    # ---- apply cache effects (stamps encode within-window order)
    stamp = (wi.stamp_base + ar)[None, :]
    enb = jnp.broadcast_to(jnp.asarray(en), (TL, K))
    l1i = cachemod.touch(l1i, pI.set_idx, pI.way,
                         touch_i & retired & enb,
                         _row_word(pI.row, pI.way), stamp)
    d_word = _row_word(pD.row, pD.way)
    if mesi_local:
        d_word = cachemod.with_state(
            d_word, jnp.where(is_wr & (pD.state == E), M, pD.state))
    l1d = cachemod.touch(l1d, pD.set_idx, pD.way,
                         touch_d & retired & enb, d_word, stamp)
    if not shared_l2:
        l2 = cachemod.touch(l2, pL2.set_idx, pL2.way,
                            (mem_l2 | comp_l2) & retired & enb,
                            _row_word(pL2.row, pL2.way), stamp)

    # Window fills — see core.py _apply_fills commentary.
    def _apply_fills(cache, fills, probe, fill_state, cp):
        act = fills & retired & enb
        st_row = cachemod.word_state(probe.row)       # [A, T, K]
        invalid = st_row == cachemod.I
        has_inv = invalid.any(axis=0)
        first_inv = jnp.argmax(invalid, axis=0)
        lru_way = jnp.argmin(cachemod.word_stamp(probe.row), axis=0)
        vic_way = jnp.where(has_inv, first_inv, lru_way)
        fway = jnp.where(probe.hit, probe.way,
                         vic_way).astype(jnp.int32)
        new_word = cachemod.pack_word(
            line.astype(jnp.int32), stamp, fill_state)
        if cp.replacement == "round_robin":
            adv = act & ~probe.hit
            rr = jnp.take_along_axis(cache.rr_ptr, probe.set_idx,
                                     axis=1)
            A = cache.word.shape[0]
            fway = jnp.where(probe.hit, probe.way,
                             jnp.where(has_inv, first_inv, rr % A))
            cache = cache._replace(rr_ptr=cache.rr_ptr.at[
                jnp.where(adv, rows[:, None], TL), probe.set_idx].set(
                (rr + 1) % A, mode="drop"))
        vic_word = _row_word(probe.row, fway)
        vic_tag = cachemod.word_tag(vic_word).astype(jnp.int64)
        vic_state = jnp.where(probe.hit, I, cachemod.word_state(vic_word))
        cache = cache._replace(word=cache.word.at[
            fway, jnp.where(act, rows[:, None], TL), probe.set_idx].set(
            new_word, mode="drop"))
        return cache, vic_tag, vic_state

    if not shared_l2:
        l1d, _, _ = _apply_fills(
            l1d, fill_d, pD,
            jnp.where(is_wr, M, S).astype(jnp.int32), params.l1d)
        l1i, _, _ = _apply_fills(
            l1i, fill_i, pI,
            jnp.full((TL, K), S, dtype=jnp.int32), params.l1i)

    # ---- branch-predictor table: last retired write per slot wins
    bp_table = wi.bp_table
    if bidx is not None:
        wr_ev = is_br & retired & enb
        later_same = (earlier.transpose(0, 2, 1) & same_slot
                      & wr_ev[:, None, :]).any(axis=2)
        winner = wr_ev & ~later_same
        SZ = params.core.bp_size
        if params.num_tiles * K * SZ <= dense.DENSE_MAX_ELEMS:
            # Dense masked update vs scatter: the branch keys on the
            # GLOBAL T (both forms give identical values — one winner
            # per slot — so the lax and blocked paths always agree).
            oh = (bidx[:, :, None]
                  == jnp.arange(SZ, dtype=jnp.int32)[None, None, :]) \
                & winner[:, :, None]
            wrote = oh.any(axis=1)
            val = (oh & taken[:, :, None]).any(axis=1)
            bp_table = jnp.where(wrote, val, bp_table)
        else:
            bp_table = bp_table.at[
                rows[:, None], jnp.where(winner, bidx, SZ)
            ].set(taken, mode="drop")

    # ---- counters

    def msum(mask, val=1):
        v = jnp.asarray(val)
        v = jnp.broadcast_to(v, (TL, K)) if v.ndim < 2 else v
        return jnp.sum(jnp.where(mask & retired & enb, v.astype(jnp.int64),
                                 0), axis=1)

    zero = jnp.zeros(TL, dtype=jnp.int64)
    ctr_inc = jnp.stack([
        msum(is_comp, icount_ev)
        + msum((is_mem & ((arg2 & 0xFF) == 0)) | is_br),     # icount
        msum(is_comp, icount_ev) + msum(is_br),              # l1i_access
        msum(is_comp & ~pI.hit & ~comp_fwd, n_lines),        # l1i_miss
        msum(is_rd),                                         # l1d_read
        msum(is_rd & ~l1_ok & ~mem_fwd),                     # l1d_read_miss
        msum(is_wr),                                         # l1d_write
        msum(is_wr & ~l1_ok & ~mem_fwd),                     # l1d_write_miss
        zero if shared_l2
        else msum(mem_l2 | comp_l2 | l2_fill_cand),          # l2_access
        zero if shared_l2 else msum(l2_fill_cand),           # l2_miss
        msum(is_br),                                         # branches
        msum(is_br & ~correct),                              # mispredicts
        msum(is_spawn),                                      # spawns
    ])

    # ---- record banked chain elements ([T, K] window results -> the
    # [P, T] chain arrays, via a dense slot one-hot — no scatter ops).
    if P > 0:
        bank_mark = jnp.stack(bank_marks, axis=1)    # [T, K]
        bank_slot = jnp.stack(bank_slots, axis=1)
        bank_delta = jnp.stack(bank_deltas, axis=1)
        kind_ev = jnp.where(is_comp, PEND_IFETCH,
                            jnp.where(is_wr, PEND_EX_REQ, PEND_SH_REQ))
        req_val = kind_ev.astype(jnp.int64) | (line << 8)
        extra_val = jnp.where(
            is_comp,
            cost_ps + fetch_ps
            + (0 if shared_l2 else (n_lines - 1) * l2_ps),
            jnp.int64(0))
        slot_oh = (bank_slot[None] == jnp.arange(P)[:, None, None]) \
            & bank_mark[None]                        # [P, T, K]
        anyb = slot_oh.any(axis=2)

        def put(dst, val):
            v = jnp.sum(jnp.where(slot_oh, val[None], 0),
                        axis=2).astype(dst.dtype)
            return jnp.where(anyb, v, dst)

        mq_req = put(wi.mq_req, req_val)
        mq_delta = put(wi.mq_delta, bank_delta)
        mq_extra = put(wi.mq_extra, extra_val)
        mq_count = nm
        chain_rel = jnp.where(nm > 0, rel, 0)
    else:
        mq_req = mq_delta = mq_extra = mq_count = chain_rel = None

    return WindowOut(
        clock=clk, n_ret=n_ret, bp_table=bp_table,
        l1i_word=l1i.word, l1i_rr=l1i.rr_ptr,
        l1d_word=l1d.word, l1d_rr=l1d.rr_ptr,
        l2_word=None if shared_l2 else l2.word,
        l2_rr=None if shared_l2 else l2.rr_ptr,
        ctr_inc=ctr_inc,
        spawn_mask=spawn_mask, spawn_child=child.astype(jnp.int32),
        spawn_land=spawn_land,
        chain_rel=chain_rel, mq_count=mq_count,
        mq_req=mq_req, mq_delta=mq_delta, mq_extra=mq_extra,
    )


def _row_word(row: jnp.ndarray, way: jnp.ndarray) -> jnp.ndarray:
    """[A, ...] gathered set row x [...] way -> [...] line word."""
    return jnp.take_along_axis(row, way[None], axis=0)[0]


# ---------------------------------------------------- pallas dispatch

def run_window(params: SimParams, vp: VariantParams, wi: WindowIn,
               s_ids: int, mode: str) -> WindowOut:
    """Dispatch the walk: inline lax ('off') or one fused pallas_call
    gridded over tile blocks ('interpret' / 'tpu')."""
    if mode == "off":
        return window_walk(params, vp, wi, s_ids)
    return dispatch.run_fused(
        lambda wi2, vp2: window_walk(params, vp2, wi2, s_ids),
        wi, vp, WINDOW_IN_AXES, WindowOut, WINDOW_OUT_AXES,
        params.num_tiles, mode, "window_walk")


def shard_local_window_in(wi: WindowIn, shard_idx, tiles_local: int
                          ) -> WindowIn:
    """Slice every walk operand to one shard's ``tiles_local`` tiles
    along its declared tile axis (``WINDOW_IN_AXES``; None-axis leaves —
    the quantum boundary, the model-enable mask — replicate).

    ``shard_idx`` is ``lax.axis_index`` inside the live shard_map; the
    structural gates (tests/test_sharding.py, tools/run_tests.sh) pass a
    CONCRETE 0 instead, which yields the exact per-shard shapes without
    needing a mesh — the CPU-checkable form of the shard-local claim."""

    def slc(name, leaf):
        ax = WINDOW_IN_AXES[name]
        if leaf is None or ax is None:
            return leaf
        return jax.lax.dynamic_slice_in_dim(
            leaf, shard_idx * tiles_local, tiles_local, axis=ax)

    return WindowIn(**{f: slc(f, v) for f, v in zip(WindowIn._fields, wi)})


def run_window_sharded(params: SimParams, vp: VariantParams, wi: WindowIn,
                       s_ids: int, mode: str) -> WindowOut:
    """The walk under ``tpu/tile_shards`` > 1 (inside the quantum
    program's shard_map, parallel/mesh.shard_wrap): slice every operand
    to this shard's T/S tiles along its declared tile axis, run the
    UNCHANGED walk on the slice, and tiled-all_gather each output back
    to the full [T] arrays the apply shell expects.

    Bit-identity is by construction: ``window_walk`` is per-tile
    independent and shape-polymorphic over the tile axis (TL =
    wi.clock.shape[0]; ``wi.tile_ids`` carries GLOBAL ids, so sliced
    spawn targets stay correct), and a tiled all_gather over the mesh
    axis concatenates the shard blocks back in exact tile order.  The
    walk itself — the PROFILE.md round-5 cost center — executes with
    ZERO cross-device traffic; the only collectives this path adds are
    the output all_gathers (one per live WindowOut leaf, counted by the
    structural gate in tools/run_tests.sh)."""
    from graphite_tpu.parallel.mesh import TILE_AXIS

    shards = params.tile_shards
    TL = params.num_tiles // shards
    wi_l = shard_local_window_in(wi, jax.lax.axis_index(TILE_AXIS), TL)
    if mode == "off":
        out_l = window_walk(params, vp, wi_l, s_ids)
    else:
        out_l = dispatch.run_fused(
            lambda wi2, vp2: window_walk(params, vp2, wi2, s_ids),
            wi_l, vp, WINDOW_IN_AXES, WindowOut, WINDOW_OUT_AXES,
            TL, mode, "window_walk")

    def gather(name, leaf):
        if leaf is None:
            return None
        return jax.lax.all_gather(leaf, TILE_AXIS,
                                  axis=WINDOW_OUT_AXES[name], tiled=True)

    return WindowOut(**{f: gather(f, v)
                        for f, v in zip(WindowOut._fields, out_l)})


# ------------------------------------------------ round-12 fast-forward

class FFIn(NamedTuple):
    """Fast-forward-walk operands: the hit/compute-only subset of the
    window operands over an [T, F] span (F = core._ff_width windows'
    worth of events).  No chain state, no iocoom rings, no L2, no rr
    pointers — the leg statically excludes every event class that could
    need them."""

    meta: jnp.ndarray           # [3, T, F] int32 (op, arg, arg2)
    addr: jnp.ndarray           # [T, F] int64
    valid_ev: jnp.ndarray       # [T, F] bool (pos < N & candidate)
    tile_active: jnp.ndarray    # [T] bool fast-forward candidates
    clock: jnp.ndarray          # [T] int64
    period_ps: jnp.ndarray      # [T, NUM_DVFS_MODULES] int32
    bp_table: jnp.ndarray       # [T, bp_size] bool
    l1i_word: jnp.ndarray       # [A, T, sets] int64
    l1d_word: jnp.ndarray       # [A, T, sets] int64
    boundary: jnp.ndarray       # [] int64
    models_enabled: jnp.ndarray  # [] bool
    stamp_base: jnp.ndarray     # [] int32


FF_IN_AXES = dict(
    meta=1, addr=0, valid_ev=0, tile_active=0, clock=0, period_ps=0,
    bp_table=0, l1i_word=1, l1d_word=1, boundary=None,
    models_enabled=None, stamp_base=None,
)


class FFOut(NamedTuple):
    clock: jnp.ndarray          # [T] int64
    n_ret: jnp.ndarray          # [T] int32 (0 on every non-engaged tile)
    bp_table: jnp.ndarray       # [T, bp_size] bool
    l1i_word: jnp.ndarray       # [A, T, sets] int64 (touch stamps only)
    l1d_word: jnp.ndarray       # [A, T, sets] int64
    ctr_inc: jnp.ndarray        # [len(WINDOW_CTRS), T] int64


FF_OUT_AXES = dict(clock=0, n_ret=0, bp_table=0, l1i_word=1, l1d_word=1,
                   ctr_inc=1)


def fast_forward_walk(params: SimParams, vp: VariantParams,
                      fi: FFIn) -> FFOut:
    """Price the longest hit/compute-only event prefix of each candidate
    tile in CLOSED FORM (round-12, ``tpu/fast_forward``).

    Eligible events are exactly the window classes whose pricing reads
    nothing an earlier in-span event can change: COMPUTE with an L1I
    hit, BRANCH, and MEM reads/writes with a writable L1D hit.  Pure
    hits install no lines — touches move stamps (not tags) and the MESI
    E->M upgrade never changes hit-ness or writability — so probing the
    whole span against SPAN-START cache state yields the identical
    hits, dts, and counters the detailed window rounds would produce
    event by event.  With no stall/sync floors in the span, the
    window's max-plus prefix degenerates to a cumulative sum, so the
    span's clock advance, commit cut (pre-clock < ``_ff_bound``), and
    counter accumulation are all one reduction instead of F engine
    rounds.  Within-span branch-predictor RAW forwards the last earlier
    committed write per table slot — the same rule the window applies
    within a round and the table carries across rounds, fused over the
    span (commits form a prefix, so writer visibility is exact).

    A tile ENGAGES only when its committable prefix beats one detailed
    window round (n_commit > K); otherwise the walk returns it
    untouched and the detailed machinery proceeds — the fall-back rule
    of the adaptive cadence.  Committed spans write the same LRU-touch
    scatter-max, E->M upgrades (propagated sticky within the span, so a
    later read of an upgraded line carries M exactly as a post-upgrade
    window probe would), and predictor-table winners the window rounds
    would have.  Pure and per-tile independent like ``window_walk`` —
    the same function serves the lax path, the fused Pallas kernel, and
    the shard-sliced path."""
    K = params.block_events
    TL = fi.clock.shape[0]
    F = fi.addr.shape[1]
    line_bits = params.line_size.bit_length() - 1
    mesi_local = params.protocol_kind == "sh_l2_mesi"
    rows = jnp.arange(TL)

    l1i = cachemod.CacheArrays(word=fi.l1i_word, rr_ptr=None)
    l1d = cachemod.CacheArrays(word=fi.l1d_word, rr_ptr=None)

    valid_ev = fi.valid_ev
    op, arg, arg2 = fi.meta[0], fi.meta[1], fi.meta[2]
    op = jnp.where(valid_ev, op, EventOp.NOP)
    en = fi.models_enabled

    p_core = fi.period_ps[:, int(DVFSModule.CORE)][:, None]
    p_l1i = fi.period_ps[:, int(DVFSModule.L1_ICACHE)][:, None]
    p_l1d = fi.period_ps[:, int(DVFSModule.L1_DCACHE)][:, None]
    l1i_ps = _lat(vp.l1i_access_cycles, p_l1i)
    l1d_ps = _lat(vp.l1d_access_cycles, p_l1d)
    cycle_ps = _lat(1, p_core)

    line = fi.addr >> line_bits
    is_comp = op == EventOp.COMPUTE
    is_br = op == EventOp.BRANCH
    is_rd = op == EventOp.MEM_READ
    is_wr = op == EventOp.MEM_WRITE          # atomics stay complex
    is_mem = is_rd | is_wr

    # ---- span-start probes; eligibility = the miss-free window classes
    pI = cachemod.probe(l1i, line, params.l1i.num_sets)
    pD = cachemod.probe(l1d, line, params.l1d.num_sets)
    writable = pD.state >= (E if mesi_local else M)
    l1_ok = pD.hit & (is_rd | writable)
    elig = ((is_comp & pI.hit) | is_br | (is_mem & l1_ok)) \
        & valid_ev & fi.tile_active[:, None] & en
    # Leading eligible run (integer cumsum, not cumprod — the engine is
    # all-integer and the Pallas path lowers it as such).
    lead = jnp.cumsum((~elig).astype(jnp.int32), axis=1) == 0

    ar = jnp.arange(F)
    earlier = ar[None, :, None] > ar[None, None, :]           # [1, F, F]

    # ---- branch predictor: last earlier in-lead write per slot wins
    # (fuses the window's within-round RAW with its cross-round table
    # reads; exact because commits are a prefix of ``lead``).
    if params.core.bp_type == "none":
        correct = jnp.ones_like(is_br)
        bidx = None
    else:
        bidx = (fi.addr % params.core.bp_size).astype(jnp.int32)
        tbl_pred = jnp.take_along_axis(fi.bp_table, bidx, axis=1)
        same_slot = bidx[:, :, None] == bidx[:, None, :]      # [T, Fj, Fi]
        taken = arg != 0
        w_mask = earlier & same_slot & (is_br & lead)[:, None, :]
        has_w = w_mask.any(axis=2)
        last_w = jnp.argmax(
            jnp.where(w_mask, ar[None, None, :], -1), axis=2)
        pred = jnp.where(has_w, jnp.take_along_axis(taken, last_w, axis=1),
                         tbl_pred)
        correct = pred == taken

    # ---- per-event dt — the window's formulas with every fill/L2/floor
    # term structurally zero for the eligible classes.
    icount_ev = jnp.maximum(arg2 & ((1 << 20) - 1), 0).astype(jnp.int64)
    cost_ps = _lat(jnp.maximum(arg, 0), p_core)
    dt = jnp.zeros((TL, F), dtype=jnp.int64)
    dt = jnp.where(is_comp, cost_ps + icount_ev * l1i_ps, dt)
    dt = jnp.where(is_br,
                   jnp.where(correct, cycle_ps,
                             _lat(vp.bp_mispredict_penalty, p_core))
                   + l1i_ps, dt)
    dt = jnp.where(is_mem, l1d_ps, dt)

    # ---- closed-form commit: clock BEFORE event j under the bound.
    bound = _ff_bound(params, vp, fi.boundary)
    dtm = jnp.where(lead, dt, 0)
    csum = jnp.cumsum(dtm, axis=1)
    pre = fi.clock[:, None] + csum - dtm
    commit0 = lead & (pre < bound)           # dt >= 0 => still a prefix
    n_commit = jnp.sum(commit0, axis=1).astype(jnp.int32)
    # A tile engages only when the span prices RUN-AHEAD the detailed
    # machinery cannot reach: commits past the window's own (possibly
    # quantum-spanned) bound, admitted by the ``fast_forward_span``
    # budget alone.  At span 0 ``bound`` equals the window bound, no
    # commit can cross it, and the leg stays dormant — within-bound
    # work belongs to the wide fast-forward WINDOW rounds (core.py
    # cadence), which price it without an extra round.
    wb = _spanned_bound(params, vp, fi.boundary)
    engage = fi.tile_active & (n_commit > K) \
        & (commit0 & (pre >= wb)).any(axis=1)
    commit = commit0 & engage[:, None]
    n_ret = jnp.where(engage, n_commit, 0)
    clock = fi.clock + jnp.sum(jnp.where(commit, dt, 0), axis=1)

    # ---- batched LRU touches (stamps keep within-span order; all span
    # stamps exceed every pre-span stamp, so relative LRU age is the
    # window rounds' exactly).
    stamp = (fi.stamp_base + ar)[None, :]
    l1i = cachemod.touch(l1i, pI.set_idx, pI.way, is_comp & commit,
                         _row_word(pI.row, pI.way), stamp)
    d_word = _row_word(pD.row, pD.way)
    if mesi_local:
        # Sticky E->M: any committed earlier-or-self write of the line
        # upgrades every later in-span touch word of that line, so the
        # scatter-max lands M exactly as post-upgrade window probes
        # would have.
        ge = ar[None, :, None] >= ar[None, None, :]
        same_line_f = line[:, :, None] == line[:, None, :]
        upgraded = (ge & same_line_f & (commit & is_wr)[:, None, :]
                    ).any(axis=2) & (pD.state == E)
        d_word = cachemod.with_state(
            d_word, jnp.where(is_mem & upgraded, M, pD.state))
    l1d = cachemod.touch(l1d, pD.set_idx, pD.way, is_mem & commit,
                         d_word, stamp)

    # ---- predictor table: last committed write per slot wins (the
    # window's winner rule over the span; dense-vs-scatter keyed on the
    # GLOBAL T like the window, so lax and blocked paths agree).
    bp_table = fi.bp_table
    if bidx is not None:
        wr_ev = is_br & commit
        later_same = (earlier.transpose(0, 2, 1) & same_slot
                      & wr_ev[:, None, :]).any(axis=2)
        winner = wr_ev & ~later_same
        SZ = params.core.bp_size
        if params.num_tiles * F * SZ <= dense.DENSE_MAX_ELEMS:
            oh = (bidx[:, :, None]
                  == jnp.arange(SZ, dtype=jnp.int32)[None, None, :]) \
                & winner[:, :, None]
            wrote = oh.any(axis=1)
            val = (oh & taken[:, :, None]).any(axis=1)
            bp_table = jnp.where(wrote, val, bp_table)
        else:
            bp_table = bp_table.at[
                rows[:, None], jnp.where(winner, bidx, SZ)
            ].set(taken, mode="drop")

    # ---- counters: the window's rows with every miss/L2/spawn term
    # structurally zero.
    def msum(mask, val=1):
        v = jnp.asarray(val)
        v = jnp.broadcast_to(v, (TL, F)) if v.ndim < 2 else v
        return jnp.sum(jnp.where(mask & commit, v.astype(jnp.int64), 0),
                       axis=1)

    zero = jnp.zeros(TL, dtype=jnp.int64)
    ctr_inc = jnp.stack([
        msum(is_comp, icount_ev)
        + msum((is_mem & ((arg2 & 0xFF) == 0)) | is_br),     # icount
        msum(is_comp, icount_ev) + msum(is_br),              # l1i_access
        zero,                                                # l1i_miss
        msum(is_rd),                                         # l1d_read
        zero,                                                # l1d_read_miss
        msum(is_wr),                                         # l1d_write
        zero,                                                # l1d_write_miss
        zero,                                                # l2_access
        zero,                                                # l2_miss
        msum(is_br),                                         # branches
        msum(is_br & ~correct),                              # mispredicts
        zero,                                                # spawns
    ])

    return FFOut(clock=clock, n_ret=n_ret, bp_table=bp_table,
                 l1i_word=l1i.word, l1d_word=l1d.word, ctr_inc=ctr_inc)


def run_fast_forward(params: SimParams, vp: VariantParams, fi: FFIn,
                     mode: str) -> FFOut:
    """Dispatch the fast-forward walk: inline lax ('off') or one fused
    pallas_call gridded over tile blocks — the same dispatcher contract
    as ``run_window``, so the Pallas walk and the analytic span cannot
    drift (ONE walk body serves both)."""
    if mode == "off":
        return fast_forward_walk(params, vp, fi)
    return dispatch.run_fused(
        lambda fi2, vp2: fast_forward_walk(params, vp2, fi2),
        fi, vp, FF_IN_AXES, FFOut, FF_OUT_AXES,
        params.num_tiles, mode, "fast_forward_walk")


def shard_local_ff_in(fi: FFIn, shard_idx, tiles_local: int) -> FFIn:
    """Slice every fast-forward operand to one shard's tiles along its
    declared axis (``FF_IN_AXES``; None-axis leaves replicate) — the
    ``shard_local_window_in`` rule on the FF operand set."""

    def slc(name, leaf):
        ax = FF_IN_AXES[name]
        if ax is None:
            return leaf
        return jax.lax.dynamic_slice_in_dim(
            leaf, shard_idx * tiles_local, tiles_local, axis=ax)

    return FFIn(**{f: slc(f, v) for f, v in zip(FFIn._fields, fi)})


def run_fast_forward_sharded(params: SimParams, vp: VariantParams,
                             fi: FFIn, mode: str) -> FFOut:
    """The fast-forward walk under ``tpu/tile_shards`` > 1: slice to the
    shard's T/S tiles, run the UNCHANGED walk, tiled-all_gather the
    outputs — bit-identical to the unsharded leg by the same
    construction as ``run_window_sharded`` (per-tile independent,
    shape-polymorphic, exact block reconstruction)."""
    from graphite_tpu.parallel.mesh import TILE_AXIS

    shards = params.tile_shards
    TL = params.num_tiles // shards
    fi_l = shard_local_ff_in(fi, jax.lax.axis_index(TILE_AXIS), TL)
    if mode == "off":
        out_l = fast_forward_walk(params, vp, fi_l)
    else:
        out_l = dispatch.run_fused(
            lambda fi2, vp2: fast_forward_walk(params, vp2, fi2),
            fi_l, vp, FF_IN_AXES, FFOut, FF_OUT_AXES,
            TL, mode, "fast_forward_walk")

    def gather(name, leaf):
        return jax.lax.all_gather(leaf, TILE_AXIS,
                                  axis=FF_OUT_AXES[name], tiled=True)

    return FFOut(**{f: gather(f, v)
                    for f, v in zip(FFOut._fields, out_l)})
