"""The chain replay iteration's classify/elect/combine/price sub-chain.

Each ``chain_fast_pass`` iteration (engine/resolve.py) serves every
tile's current chain head with the round loop's exact math.  Its cost on
TPU is the long chain of small sequential table ops over the shared hash
index — victim-way exclusion tables, the (home, dset, way) FCFS
election, fan-out/owner delivery budgets, SH-combining rep tables — plus
the directory transition and the zero-load timing legs, each a [T]-wide
op paying its own dispatch.  ``chain_classify`` extracts that whole
sub-chain as ONE pure function shared by both paths:

  * lax (``tpu/pallas_kernels`` off): called inline — the program is
    the pre-round-10 iteration, value for value;
  * fused (interpret / tpu): the same function inside one
    ``pl.pallas_call`` (single grid step: the hash tables are global
    over tiles, and [T]- and [H]-sized operands fit VMEM comfortably at
    every supported T), so the P replay iterations cost P kernel
    dispatches instead of P x dozens.

What stays OUTSIDE the kernel, by design:
  * the [P, T] chain-head gathers and the big dir_word / dir_sharers
    row gathers (one XLA gather each — not the dispatch chain);
  * the DRAM queue-model probe (its ring state is loop-carried through
    the engine; with ``dram/queue_model_enabled = false`` the kernel
    also absorbs the completion math and the per-line floor write);
  * the apply scatters (directory install, sharer-bitmap add, cache
    invalidation sweeps and fills, counters) — stacked multi-field
    scatters since round 6.

All values are integer and the function is deterministic, so
kernels-on == kernels-off bit-exactly (tests/test_kernels.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from graphite_tpu.engine import cache as cachemod
from graphite_tpu.engine import dense
from graphite_tpu.engine import directory as dirmod
from graphite_tpu.engine import noc
from graphite_tpu.engine.kernels import dispatch
from graphite_tpu.engine.state import (dword_owner, dword_stamp,
                                       dword_state, dword_tag)
from graphite_tpu.engine.vparams import VariantParams
from graphite_tpu.params import SimParams

I, S, O, E, M = (cachemod.I, cachemod.S, cachemod.O, cachemod.E,
                 cachemod.M)

# Control-message payload bytes (request/inv/ack packets).
CTRL_BYTES = 8

# Per-target budget of point-to-point owner flush/downgrade deliveries
# per conflict round / replay iteration.
J_OWN = 8


def _lat(cycles, period_ps):
    return jnp.asarray(cycles, jnp.int64) * jnp.asarray(period_ps, jnp.int64)


class ChainIn(NamedTuple):
    """One replay iteration's classify operands (all [T] unless noted).
    The hash tables are global over tiles, so there is no tile blocking
    — every axis entry is None (single grid step)."""

    active: jnp.ndarray      # bool
    is_ex: jnp.ndarray       # bool
    is_if: jnp.ndarray       # bool
    line: jnp.ndarray        # int64
    issue: jnp.ndarray       # int64
    extra: jnp.ndarray       # int64 (local cost owed at completion)
    home: jnp.ndarray        # int32
    dset: jnp.ndarray        # int32
    fidx: jnp.ndarray        # int32 flat (home * ndsets + dset)
    hidx: jnp.ndarray        # int32 hash slot of the line
    drow: jnp.ndarray        # [T, A] int64 gathered directory words
    dsharers: jnp.ndarray    # [T, A, W] uint64 gathered sharer words
    p_net: jnp.ndarray       # int32 periods
    p_dir: jnp.ndarray
    p_l2: jnp.ndarray
    p_l1d: jnp.ndarray
    p_l1i: jnp.ndarray
    p_core: jnp.ndarray
    ftbl: Optional[jnp.ndarray]  # [2, H] int64 — present iff the
    #   kernel owns the floor write (DRAM queue model off)


CHAIN_IN_AXES = {f: None for f in ChainIn._fields}


class ChainOut(NamedTuple):
    way: jnp.ndarray            # [T] int32 (post-combining)
    hit: jnp.ndarray            # bool — directory-entry hit
    serve: jnp.ndarray          # bool — election winners
    serve_all: jnp.ndarray      # bool — winners + combining members
    member: jnp.ndarray         # bool
    member_add: jnp.ndarray     # bool — member bit-add guard
    hard_stop: jnp.ndarray      # bool — chain demotes to the round loop
    fan_go: jnp.ndarray         # bool — in-pass fan-out serves
    owner_leg: jnp.ndarray      # bool — served owner flush/downgrade
    evicting: jnp.ndarray       # bool
    owner: jnp.ndarray          # [T] int32 owner tile
    ow_slot: jnp.ndarray        # [T] int32 min(posr, J_OWN - 1)
    down_to: jnp.ndarray        # [T] int32 owner downgrade state
    new_state: jnp.ndarray      # [T] int32 directory entry after
    new_owner: jnp.ndarray      # [T] int32
    delta_sh: jnp.ndarray       # [T, W] uint64 sharer-bitmap delta
    dram_read: jnp.ndarray      # bool — act.dram_read (pre-serve mask)
    dram_write: jnp.ndarray     # bool — act.dram_write
    need_read: jnp.ndarray      # bool — serve_all & dram_read
    dram_wb: jnp.ndarray        # bool — dram_write & serve_all
    t_dir: jnp.ndarray          # [T] int64
    owner_ps: jnp.ndarray       # [T] int64
    inv_ps: jnp.ndarray         # [T] int64 (zeros with fanout off)
    reply_ps: jnp.ndarray       # [T] int64
    from_dram_ps: jnp.ndarray   # [T] int64
    dram_arrival: jnp.ndarray   # [T] int64
    l1_fill_ps: jnp.ndarray     # [T] int64
    inv_bool: Optional[jnp.ndarray]   # [KF, T] bool (fanout only)
    line_fr: Optional[jnp.ndarray]    # [KF] int64 (fanout only)
    inv_count: jnp.ndarray      # [T] int64
    completion: Optional[jnp.ndarray]  # [T] int64 (queue off only)
    t_data: Optional[jnp.ndarray]      # [T] int64 (queue off only)
    ftbl: Optional[jnp.ndarray]        # [2, H] int64 (queue off only)


CHAIN_OUT_AXES = {f: None for f in ChainOut._fields}


def chain_classify(params: SimParams, vp: VariantParams, ci: ChainIn,
                   H: int) -> ChainOut:
    """One replay iteration's classification — engine/resolve.py's
    slot_body from the directory probe through the timing legs, verbatim
    apart from the operand plumbing (see chain_fast_pass for the
    semantics commentary)."""
    T = params.num_tiles
    A = params.directory.associativity
    W = ci.dsharers.shape[2]
    ndsets = params.directory.num_sets
    rows = jnp.arange(T)
    shared_l2 = params.shared_l2
    fanout = params.fanout_replay
    KF = min(params.max_inv_fanout_per_round, T)

    active, is_ex, is_if = ci.active, ci.is_ex, ci.is_if
    line, issue = ci.line, ci.issue
    home, dset, fidx, hidx = ci.home, ci.dset, ci.fidx, ci.hidx
    p_net, p_dir = ci.p_net, ci.p_dir
    ack_ps = _lat(vp.inv_ack_cycles, ci.p_core)

    # ---- directory probe at (home, dset) — post-predecessor state
    drow = ci.drow                                        # [T, A]
    dstate = dword_state(drow)
    dstamp = dword_stamp(drow)
    match = (dword_tag(drow) == line[:, None].astype(jnp.int32)) \
        & (dstate != I)
    hit = match.any(axis=1) & active
    hway = jnp.argmax(match, axis=1).astype(jnp.int32)
    invalid = dstate == I

    # ---- victim way for allocs: invalid first, then stamp-LRU,
    # ways held by this slot's hit elements excluded
    fhash = (dense.fmix64(fidx.astype(jnp.int64))
             % jnp.uint64(H)).astype(jnp.int32)
    used_tbl = jnp.zeros((H, A), dtype=bool).at[
        jnp.where(hit, fhash, H), hway].set(True, mode="drop")
    hway_used = used_tbl[fhash]                            # [T, A]
    NEVER = jnp.int32(2**31 - 1)
    vkey = jnp.where(hway_used, NEVER,
                     jnp.where(invalid, -1, dstamp))
    miss_way = jnp.argmin(vkey, axis=1).astype(jnp.int32)
    can_alloc = active & ~hit & (jnp.take_along_axis(
        vkey, miss_way[:, None], axis=1)[:, 0] != NEVER)
    way = jnp.where(hit, hway, miss_way)

    # ---- way-slot election
    am = (home.astype(jnp.int64) * ndsets + dset) * A + way
    aidx = (dense.fmix64(am) % jnp.uint64(H)).astype(jnp.int32)
    packed = dense.fcfs_keys(active, issue)
    wslot = dense.elect(active, packed, aidx, H)

    # ---- transition against the replayed entry
    way_word = jnp.take_along_axis(drow, way[:, None], axis=1)[:, 0]
    way_state = dword_state(way_word)
    way_owner = dword_owner(way_word)
    dsharers = ci.dsharers                                # [T, A, W]
    entry_row = jnp.take_along_axis(
        dsharers, way[:, None, None], axis=1)[:, 0, :]    # [T, W]
    entry_state = jnp.where(hit, way_state, I)
    entry_owner = jnp.where(hit, way_owner, -1)
    entry_sharers = jnp.where(hit[:, None], entry_row,
                              jnp.zeros((T, W), dtype=jnp.uint64))
    act = dirmod.transition(params.protocol_kind, is_ex, rows,
                            entry_state, entry_owner, entry_sharers,
                            W, is_ifetch=is_if)
    has_inv = (act.inv_targets != jnp.uint64(0)).any(axis=1)
    vic_dead = (way_state == I) \
        | (((way_state == S) | (way_state == O))
           & (entry_row == jnp.uint64(0)).all(axis=1))
    cand0 = active & wslot & (hit | (can_alloc & vic_dead))
    if fanout:
        need_fan = cand0 & has_inv
        fan_rank = jnp.sum(
            (packed[None, :] < packed[:, None]) & need_fan[None, :]
            & need_fan[:, None], axis=1, dtype=jnp.int32)
        fan_sel = need_fan & (fan_rank < KF)
        cand = cand0 & (~has_inv | fan_sel)
    else:
        fan_rank = jnp.zeros(T, dtype=jnp.int32)
        cand = cand0 & ~has_inv
    owner = act.owner_tile
    posr = dense.grouped_rank(owner, packed, cand & act.owner_leg)
    serve = cand & ~(act.owner_leg & (posr >= J_OWN))
    owner_leg = act.owner_leg & serve
    fan_go = serve & has_inv          # in-pass fan-out serves
    evicting = serve & ~hit & (way_state != I)

    # ---- SH combining within the slot (the round loop's combining)
    sh_ok_e = (entry_state == I) | (entry_state == S)
    if shared_l2:
        sh_ok_e = sh_ok_e & (entry_state != I)
    ex_any_t = jnp.zeros((H,), dtype=bool).at[
        jnp.where(active & is_ex, hidx, H)].set(True, mode="drop")
    rep_sh = serve & ~is_ex & sh_ok_e
    rep_line_t = jnp.full((H,), -1, jnp.int64).at[
        jnp.where(rep_sh, hidx, H)].set(line, mode="drop")
    rep_way_t = jnp.zeros((H,), jnp.int32).at[
        jnp.where(rep_sh, hidx, H)].set(way, mode="drop")
    member = active & ~serve & ~is_ex & sh_ok_e & ~ex_any_t[hidx] \
        & (rep_line_t[hidx] == line)
    way = jnp.where(member, rep_way_t[hidx], way)
    serve_all = serve | member
    stop_inv = has_inv if not fanout else jnp.zeros_like(has_inv)
    hard_stop = active & ~serve_all \
        & (stop_inv | (can_alloc & ~vic_dead) | (~hit & ~can_alloc)
           | (act.owner_leg & (posr >= J_OWN)))

    # ---- timing: the round loop's zero-load path for a fast element
    net_req = noc.unicast_ps(params.net_memory, rows, home,
                             CTRL_BYTES, p_net, params.mesh_width,
                             vnet=vp.net_memory)
    p_net_home = jnp.take_along_axis(p_net, home, axis=0)
    reply_ps = noc.unicast_ps(params.net_memory, home, rows,
                              params.line_size + CTRL_BYTES,
                              p_net_home, params.mesh_width,
                              vnet=vp.net_memory)
    dir_ps = _lat(vp.dir_access_cycles,
                  jnp.take_along_axis(p_dir, home, axis=0))
    arrive = issue + net_req
    t_dir = arrive + dir_ps
    p_net_own = jnp.take_along_axis(p_net, owner, axis=0)
    if shared_l2:
        l2_own_ps = _lat(vp.l1d_access_cycles,
                         jnp.take_along_axis(ci.p_l1d, owner, axis=0))
    else:
        l2_own_ps = _lat(vp.l2_access_cycles,
                         jnp.take_along_axis(ci.p_l2, owner, axis=0))
    leg_ps = noc.unicast_ps(params.net_memory, home, owner,
                            CTRL_BYTES, p_net_home,
                            params.mesh_width, vnet=vp.net_memory) \
        + l2_own_ps \
        + noc.unicast_ps(params.net_memory, owner, home,
                         params.line_size + CTRL_BYTES, p_net_own,
                         params.mesh_width, vnet=vp.net_memory)
    owner_ps = jnp.where(owner_leg, leg_ps, 0)
    if fanout:
        oh_fr = fan_go[None, :] & (
            jnp.arange(KF, dtype=jnp.int32)[:, None]
            == jnp.minimum(fan_rank, KF - 1)[None, :])

        def fr_sel(vals):
            return jnp.sum(jnp.where(oh_fr, vals[None, :], 0), axis=1,
                           dtype=vals.dtype)

        inv_words = jnp.sum(
            jnp.where(oh_fr[:, :, None], act.inv_targets[None, :, :],
                      jnp.uint64(0)), axis=1, dtype=jnp.uint64)
        inv_bool = dirmod.bitmap_to_bool(inv_words, T)      # [KF, T]
        home_fr = fr_sel(home)
        pnh_fr = fr_sel(p_net_home.astype(jnp.int64)).astype(jnp.int32)
        inv_ps_k = 2 * noc.max_hop_to_mask_ps(
            params.net_memory, home_fr, inv_bool, CTRL_BYTES,
            pnh_fr, params.mesh_width, vnet=vp.net_memory) \
            + fr_sel(ack_ps)
        inv_ps = jnp.where(fan_go, jnp.sum(
            jnp.where(oh_fr, inv_ps_k[:, None], 0), axis=0), 0)
        line_fr = fr_sel(line)
        kcnt = jnp.sum(inv_bool, axis=1).astype(jnp.int64)  # [KF]
        inv_count = jnp.where(fan_go, jnp.sum(
            jnp.where(oh_fr, kcnt[:, None], 0), axis=0), 0)
    else:
        inv_bool = line_fr = None
        inv_ps = jnp.zeros(T, dtype=jnp.int64)
        inv_count = jnp.zeros(T, dtype=jnp.int64)
    need_read = serve_all & act.dram_read
    if shared_l2:
        dsite = _dram_site(params, line)
        local_ctl = home == dsite
        to_dram_ps = jnp.where(local_ctl, 0, noc.unicast_ps(
            params.net_memory, home, dsite, CTRL_BYTES, p_net_home,
            params.mesh_width, vnet=vp.net_memory))
        from_dram_ps = jnp.where(local_ctl, 0, noc.unicast_ps(
            params.net_memory, dsite, home,
            params.line_size + CTRL_BYTES,
            jnp.take_along_axis(p_net, dsite, axis=0),
            params.mesh_width, vnet=vp.net_memory))
    else:
        to_dram_ps = jnp.int64(0)
        from_dram_ps = jnp.broadcast_to(jnp.int64(0), (T,))
    dram_arrival = t_dir + owner_ps + to_dram_ps
    dram_wb = act.dram_write & serve_all
    l1_fill_ps = jnp.where(
        is_if, _lat(vp.l1i_access_cycles, ci.p_l1i),
        _lat(vp.l1d_access_cycles, ci.p_l1d))

    # ---- sharer-bitmap delta + member bit-add guard (apply operands)
    delta_sh = act.new_sharers - entry_row
    req_word = (rows // 64).astype(jnp.int32)
    req_bit = jnp.uint64(1) << (rows % 64).astype(jnp.uint64)
    row_f = jnp.take_along_axis(
        dsharers, way[:, None, None], axis=1)[:, 0, :]
    own_w = jnp.take_along_axis(row_f, req_word[:, None],
                                axis=1)[:, 0]
    member_add = member & (~hit
                           | ((own_w & req_bit) == jnp.uint64(0)))

    # ---- queue-model-off tail: completion + the per-line floor write
    # fold into the kernel (with the queue on, the loop-carried ring
    # probe sits between dram_arrival and completion — the caller owns
    # that stretch and the floor write).
    if not params.dram.queue_model_enabled:
        dram_start = jnp.where(need_read, dram_arrival, 0)
        dram_ready = dram_start + vp.dram_latency_ps \
            + vp.dram_processing_ps + from_dram_ps
        t_data = jnp.maximum(t_dir + owner_ps,
                             jnp.where(need_read, dram_ready, 0))
        if fanout:
            t_data = jnp.maximum(t_data, t_dir + inv_ps)
        reply_done = t_data + reply_ps
        if shared_l2:
            completion = reply_done + l1_fill_ps + ci.extra
        else:
            completion = reply_done \
                + _lat(vp.l2_access_cycles, ci.p_l2) + l1_fill_ps \
                + ci.extra
        tkey = t_data * T + rows
        tmax_t = jnp.full((H,), -1, jnp.int64).at[
            jnp.where(serve_all, hidx, H)].max(tkey, mode="drop")
        fwin = serve_all & (tmax_t[hidx] == tkey)
        ftbl = dense.stacked_set_table(hidx, fwin,
                                       jnp.stack([line, t_data]),
                                       ci.ftbl)
    else:
        completion = t_data = ftbl = None

    return ChainOut(
        way=way, hit=hit, serve=serve, serve_all=serve_all, member=member,
        member_add=member_add, hard_stop=hard_stop, fan_go=fan_go,
        owner_leg=owner_leg, evicting=evicting, owner=owner,
        ow_slot=jnp.minimum(posr, J_OWN - 1), down_to=act.owner_downgrade_to,
        new_state=act.new_state, new_owner=act.new_owner,
        delta_sh=delta_sh, dram_read=act.dram_read,
        dram_write=act.dram_write, need_read=need_read, dram_wb=dram_wb,
        t_dir=t_dir, owner_ps=owner_ps, inv_ps=inv_ps, reply_ps=reply_ps,
        from_dram_ps=from_dram_ps, dram_arrival=dram_arrival,
        l1_fill_ps=l1_fill_ps, inv_bool=inv_bool, line_fr=line_fr,
        inv_count=inv_count, completion=completion, t_data=t_data,
        ftbl=ftbl,
    )


def _dram_site(params: SimParams, line: jnp.ndarray) -> jnp.ndarray:
    """resolve.dram_site_of_line without importing resolve (no cycles):
    the shared dense.home_fold over the controllers — one fold
    definition, so the kernel's slice->controller timing legs can never
    desynchronize from the caller's queue/counter site."""
    return dense.home_fold(line, params.dram.num_controllers) \
        * params.dram.controller_home_stride


def run_chain(params: SimParams, vp: VariantParams, ci: ChainIn,
              H: int, mode: str) -> ChainOut:
    """Dispatch the classify: inline lax ('off') or one fused
    pallas_call per replay iteration ('interpret' / 'tpu')."""
    if mode == "off":
        return chain_classify(params, vp, ci, H)
    return dispatch.run_fused(
        lambda ci2, vp2: chain_classify(params, vp2, ci2, H),
        ci, vp, CHAIN_IN_AXES, ChainOut, CHAIN_OUT_AXES,
        1, mode, "chain_classify")
