"""Instruction/event type taxonomy.

Mirrors the reference's InstructionType enum (reference:
common/tile/core/instruction.h:20-58) — the static types carry table-driven
costs ([core/static_instruction_costs], carbon_sim.cfg:189-200); the dynamic
types (recv/sync/spawn/stall) carry a latency computed at event-generation
time (reference: common/tile/core/instruction.h:166-200).

The TPU build folds both into one event stream: each event slot is either a
COMPUTE block (a run of non-memory instructions collapsed into an aggregate
cost — a trace-side optimization the event-driven reference doesn't need), a
single modeled instruction with a memory operand, a BRANCH, or a dynamic
event (SYNC/RECV/...).
"""

from __future__ import annotations

import enum


class InstructionType(enum.IntEnum):
    """Static instruction classes with config-table costs."""

    GENERIC = 0
    MOV = 1
    IALU = 2
    IMUL = 3
    IDIV = 4
    FALU = 5
    FMUL = 6
    FDIV = 7
    XMM_SS = 8
    XMM_SD = 9
    XMM_PS = 10
    BRANCH = 11

    @property
    def config_key(self) -> str:
        return self.name.lower()


# Order matters: index into the static-cost table array.
STATIC_COST_TYPES = [
    InstructionType.GENERIC,
    InstructionType.MOV,
    InstructionType.IALU,
    InstructionType.IMUL,
    InstructionType.IDIV,
    InstructionType.FALU,
    InstructionType.FMUL,
    InstructionType.FDIV,
    InstructionType.XMM_SS,
    InstructionType.XMM_SD,
    InstructionType.XMM_PS,
]


class EventOp(enum.IntEnum):
    """Per-slot event opcodes in the trace stream (see events/schema.py)."""

    NOP = 0          # empty slot / padding
    COMPUTE = 1      # run of non-memory instructions: cost + count aggregated
    MEM_READ = 2     # modeled data read  (lite::handleMemoryRead analog)
    MEM_WRITE = 3    # modeled data write (lite::handleMemoryWrite analog)
    BRANCH = 4       # conditional branch: predictor query + penalty on miss
    RECV = 5         # blocking user-network receive (CAPI_message_receive_w)
    SEND = 6         # user-network send (CAPI_message_send_w)
    SYNC = 7         # sync-op completion with frontend-supplied wake time
    SPAWN = 8        # thread spawn overhead event
    STALL = 9        # explicit stall until given absolute time
    DVFS_SET = 10    # change this tile's domain frequency
    ATOMIC = 11      # atomic read-modify-write (exclusive request + update)
    DONE = 12        # tile finished its stream
    BARRIER_WAIT = 13  # block until all participants arrive (SimBarrier analog,
                       # reference: common/system/sync_server.h:15-121)
    MUTEX_LOCK = 14    # FCFS simulated mutex acquire (SimMutex analog)
    MUTEX_UNLOCK = 15  # release; wakes earliest waiter
    COND_WAIT = 16     # release held mutex + park until signaled, then
                       # re-acquire (SimCond::wait, sync_server.cc:67-74)
    COND_SIGNAL = 17   # wake earliest waiter parked at signal time; lost
                       # if none (SimCond::signal, sync_server.cc:76-100)
    COND_BROADCAST = 18  # wake every waiter parked at broadcast time
    JOIN = 19          # block until the named tile's stream is DONE
                       # (ThreadManager join protocol, thread_manager.cc)
    THREAD_START = 20  # block the stream until some tile SPAWNs this one
    ENABLE_MODELS = 21   # region-of-interest start: turn timing models on
                         # (CarbonEnableModels, simulator.cc:287-301)
    DISABLE_MODELS = 22  # region-of-interest end: fast-forward (zero cost,
                         # no counters) until re-enabled
    SYSCALL = 23       # marshalled system call to the MCP's syscall server
                       # (reference: common/tile/core/syscall_model.cc packs
                       # args, common/system/syscall_server.cc:43-130 serves;
                       # arg = SyscallClass, arg2 = marshalled byte count)
    YIELD = 24         # voluntarily give up the core: the ThreadScheduler
                       # rotates the next queued stream onto this tile
                       # (CarbonThreadYield -> ThreadScheduler::yieldThread,
                       # thread_scheduler.cc:615-660; no-op when the trace
                       # has one stream per tile)


class SyscallClass(enum.IntEnum):
    """Syscall cost classes (reference: the IF_ORIG_ENUM dispatch table in
    syscall_server.cc:43-130 — open/read/write/close/access/stat/mmap/brk
    each marshal through the MCP; futex ops re-enter the sync machinery
    and therefore surface as the sync events above, not as SYSCALL)."""

    OTHER = 0
    OPEN = 1
    CLOSE = 2
    READ = 3
    WRITE = 4
    LSEEK = 5
    ACCESS = 6
    STAT = 7
    MMAP = 8
    MUNMAP = 9
    BRK = 10


class MemComponent(enum.IntEnum):
    """Memory components addressed by an access (reference:
    common/tile/memory_subsystem/memory_manager.h MemComponent)."""

    INVALID = 0
    L1_ICACHE = 1
    L1_DCACHE = 2
    L2_CACHE = 3
    DRAM_DIRECTORY = 4
    DRAM = 5


class DVFSModule(enum.IntEnum):
    """Frequency/voltage domain modules (reference: common/system/dvfs_manager.h,
    [dvfs/domains] carbon_sim.cfg:147-155)."""

    CORE = 0
    L1_ICACHE = 1
    L1_DCACHE = 2
    L2_CACHE = 3
    DIRECTORY = 4
    NETWORK_USER = 5
    NETWORK_MEMORY = 6

    @classmethod
    def parse(cls, name: str) -> "DVFSModule":
        return cls[name.strip().upper()]
