"""Hierarchical configuration system.

Schema-compatible with the reference simulator's config stack: an INI-style
file whose section headers may nest with '/' separators, layered with
command-line overrides of the form ``--section/sub/key=value``
(reference: common/config/config.hpp, common/misc/handle_args.cc:45-58,
carbon_sim.cfg).  The parser here is a small hand-written one (the
reference uses a Boost.Spirit grammar, common/config/config_file_grammar.hpp);
behavior, not implementation, is what we keep.

Values are typed on *read*: ``get_int/get_float/get_bool/get_str`` convert
the stored string, mirroring the reference's typed lookups
(common/config/config.hpp getInt/getBool/...).  Quoted strings keep their
inner text; bare words are kept verbatim.
"""

from __future__ import annotations

import copy
import os
import re
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["Config", "ConfigError", "load_config", "parse_overrides"]


class ConfigError(Exception):
    """Raised for missing keys or malformed config input."""


_SECTION_RE = re.compile(r"^\[([A-Za-z0-9_/\-\.]*)\]\s*$")
_KEY_RE = re.compile(r"^([A-Za-z0-9_\-\.]+)\s*=\s*(.*)$")

_TRUE_WORDS = {"true", "yes", "on", "1"}
_FALSE_WORDS = {"false", "no", "off", "0"}


def _strip_comment(line: str) -> str:
    """Drop a trailing ``#`` comment, honoring double-quoted strings."""
    out = []
    in_quote = False
    for ch in line:
        if ch == '"':
            in_quote = not in_quote
        elif ch == "#" and not in_quote:
            break
        out.append(ch)
    return "".join(out)


def _parse_value(raw: str) -> str:
    raw = raw.strip()
    if len(raw) >= 2 and raw[0] == '"' and raw[-1] == '"':
        return raw[1:-1]
    return raw


class Config:
    """A tree of ``section -> {key: string-value}`` with typed accessors.

    Keys are addressed by full path, e.g. ``cfg.get_int("general/total_cores")``.
    Layering: defaults < config file < CLI overrides — the same precedence
    the reference applies (file then --section/key=value flags,
    common/misc/handle_args.cc:45-58).
    """

    def __init__(self, data: Optional[Dict[str, Dict[str, str]]] = None):
        # Flat map: section-path -> {key: raw-string-value}.
        self._data: Dict[str, Dict[str, str]] = {}
        if data:
            for sec, kv in data.items():
                self._data[sec] = dict(kv)

    # ---------------------------------------------------------------- parse

    @classmethod
    def from_text(cls, text: str) -> "Config":
        cfg = cls()
        cfg.merge_text(text)
        return cfg

    @classmethod
    def from_file(cls, path: str) -> "Config":
        with open(path, "r") as f:
            return cls.from_text(f.read())

    def merge_text(self, text: str) -> None:
        section = ""
        for lineno, line in enumerate(text.splitlines(), start=1):
            line = _strip_comment(line).strip()
            if not line:
                continue
            m = _SECTION_RE.match(line)
            if m:
                section = m.group(1).strip("/")
                self._data.setdefault(section, {})
                continue
            m = _KEY_RE.match(line)
            if m:
                key, raw = m.group(1), m.group(2)
                self._data.setdefault(section, {})[key] = _parse_value(raw)
                continue
            raise ConfigError(f"malformed config line {lineno}: {line!r}")

    def merge_file(self, path: str) -> None:
        with open(path, "r") as f:
            self.merge_text(f.read())

    def merge(self, other: "Config") -> None:
        for sec, kv in other._data.items():
            self._data.setdefault(sec, {}).update(kv)

    def set(self, path: str, value: Any) -> None:
        section, _, key = path.rpartition("/")
        if not key:
            raise ConfigError(f"override path needs section/key: {path!r}")
        if isinstance(value, bool):
            value = "true" if value else "false"
        self._data.setdefault(section, {})[key] = str(value)

    # ---------------------------------------------------------------- read

    def _lookup(self, path: str) -> str:
        section, _, key = path.rpartition("/")
        try:
            return self._data[section][key]
        except KeyError:
            raise ConfigError(f"config key not found: {path!r}") from None

    def has(self, path: str) -> bool:
        section, _, key = path.rpartition("/")
        return section in self._data and key in self._data[section]

    _MISSING = object()

    def _raw(self, path: str, default: Any) -> Any:
        """Stored string for ``path``, or ``default`` if absent (and a default
        was given); raises ConfigError when absent with no default."""
        if not self.has(path):
            if default is not Config._MISSING:
                return default
            raise ConfigError(f"config key not found: {path!r}")
        return self._lookup(path)

    def get_str(self, path: str, default: Any = _MISSING) -> str:
        return self._raw(path, default)

    def get_int(self, path: str, default: Any = _MISSING) -> int:
        raw = self._raw(path, default)
        if not isinstance(raw, str):
            return raw
        try:
            return int(raw, 0)
        except ValueError:
            pass
        # Tolerate float-formatted integers (e.g. "2.0").
        try:
            f = float(raw)
        except ValueError:
            raise ConfigError(f"{path!r} is not an integer: {raw!r}") from None
        if f != int(f):
            raise ConfigError(f"{path!r} is not an integer: {raw!r}")
        return int(f)

    def get_float(self, path: str, default: Any = _MISSING) -> float:
        raw = self._raw(path, default)
        if not isinstance(raw, str):
            return raw
        try:
            return float(raw)
        except ValueError:
            raise ConfigError(f"{path!r} is not a number: {raw!r}") from None

    def get_bool(self, path: str, default: Any = _MISSING) -> bool:
        raw = self._raw(path, default)
        if not isinstance(raw, str):
            return raw
        raw = raw.strip().lower()
        if raw in _TRUE_WORDS:
            return True
        if raw in _FALSE_WORDS:
            return False
        raise ConfigError(f"{path!r} is not a boolean: {raw!r}")

    def get_list(self, path: str, default: Any = _MISSING) -> List[str]:
        """Comma-separated list value -> stripped items (empty -> [])."""
        raw = self._raw(path, default)
        if not isinstance(raw, str):
            return list(raw)
        raw = raw.strip()
        if not raw:
            return []
        return [item.strip() for item in raw.split(",") if item.strip()]

    def section(self, path: str) -> Dict[str, str]:
        return dict(self._data.get(path.strip("/"), {}))

    def sections(self) -> Iterator[str]:
        return iter(sorted(self._data.keys()))

    def copy(self) -> "Config":
        return Config(copy.deepcopy(self._data))

    # ------------------------------------------------------------- serialize

    def to_text(self) -> str:
        out: List[str] = []
        for sec in sorted(self._data.keys()):
            kv = self._data[sec]
            if sec:
                out.append(f"[{sec}]")
            for key in sorted(kv.keys()):
                val = kv[key]
                if val == "" or any(c.isspace() for c in val) or "," in val or "#" in val:
                    out.append(f'{key} = "{val}"')
                else:
                    out.append(f"{key} = {val}")
            out.append("")
        return "\n".join(out)

    def __repr__(self) -> str:
        nsec = len(self._data)
        nkey = sum(len(kv) for kv in self._data.values())
        return f"<Config {nsec} sections, {nkey} keys>"


def parse_overrides(argv: List[str]) -> Tuple[List[Tuple[str, str]], List[str]]:
    """Split ``--section/key=value`` flags from an argv list.

    Returns (overrides, remaining_args).  Mirrors the reference's CLI
    convention where any --path=value flag is a config override
    (common/misc/handle_args.cc:45-58).
    """
    overrides: List[Tuple[str, str]] = []
    rest: List[str] = []
    for arg in argv:
        if arg.startswith("--") and "=" in arg:
            path, _, value = arg[2:].partition("=")
            if "/" in path:
                overrides.append((path, value))
                continue
        rest.append(arg)
    return overrides, rest


def default_config_path() -> str:
    return os.path.join(os.path.dirname(__file__), "defaults.cfg")


def load_config(
    path: Optional[str] = None,
    overrides: Optional[List[Tuple[str, str]]] = None,
    argv: Optional[List[str]] = None,
) -> Config:
    """Load defaults, then an optional config file, then overrides."""
    cfg = Config.from_file(default_config_path())
    if path is not None:
        cfg.merge_file(path)
    if argv is not None:
        parsed, _ = parse_overrides(argv)
        for p, v in parsed:
            cfg.set(p, v)
    if overrides:
        for p, v in overrides:
            cfg.set(p, v)
    return cfg


def split_set_overrides(argv):
    """Partition ``argv`` into (positional_args, overrides) where the
    overrides are the ``sec/key=val`` payloads of ``--set sec/key=val``
    or ``--set=sec/key=val`` flags — the shared flag grammar of the
    profiling tools (tools/profile_round.py, tools/profile_phases.py),
    extracted so the two parsers cannot drift."""
    overrides: List[str] = []
    plain: List[str] = []
    it = iter(argv)
    for a in it:
        if a == "--set":
            try:
                overrides.append(next(it))
            except StopIteration:
                raise SystemExit("--set requires a sec/key=val argument")
        elif a.startswith("--set="):
            overrides.append(a[len("--set="):])
        else:
            plain.append(a)
    return plain, overrides


def apply_set_overrides(cfg: "Config", overrides) -> None:
    """Apply ``sec/key=val`` override strings onto a Config in order."""
    for ov in overrides:
        key, _, val = ov.partition("=")
        cfg.set(key, val)
