"""Typed, derived simulation parameters — the model-factory boundary.

``SimParams.from_config`` plays the role of the reference's config-selected
model factories (CoreModel::create core_model.cc:15,
MemoryManager::createMMU memory_manager.cc:29-52,
NetworkModel::createModel network_model.h:90,
QueueModel::create queue_model.h:7-39): every model variant is chosen here
from the same config keys, and the chosen variants fully determine the
shapes and constants of the jitted kernels.

Everything in this tree is a hashable Python scalar/tuple, so a
``SimParams`` can be a static argument to ``jax.jit`` — changing a model
choice recompiles, changing runtime state does not.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Optional, Tuple

from graphite_tpu.config import Config, ConfigError
from graphite_tpu.isa import STATIC_COST_TYPES, DVFSModule
from graphite_tpu.time_base import ns_to_ps


def _int_or_keyword(cfg: Config, path: str, keyword: str) -> Optional[int]:
    """Config value that is either the magic ``keyword`` (-> None) or an
    integer; anything else is a ConfigError."""
    raw = cfg.get_str(path).strip()
    if raw.lower() == keyword.lower():
        return None
    try:
        return int(raw)
    except ValueError:
        raise ConfigError(
            f"{path} must be {keyword!r} or an integer: {raw!r}") from None


def _ceil_log2(x: int) -> int:
    return max(0, (x - 1).bit_length())


def _positive(value: int, path: str) -> int:
    if value < 1:
        raise ConfigError(f"{path} must be >= 1: {value}")
    return value


def _nonneg(value: int, path: str) -> int:
    if value < 0:
        raise ConfigError(f"{path} must be >= 0: {value}")
    return value


# Engine stamp-allocation stride per round (see engine/core.STAMP_STRIDE;
# defined here so the config validator needn't import the engine): block
# windows use stamp offsets 0..K-1, the general slot and resolve fills use
# the top two, so K is capped at STRIDE - 2.
STAMP_STRIDE = 64


def _block_events(value: int) -> int:
    if not 0 <= value <= STAMP_STRIDE - 2:
        raise ConfigError(
            f"tpu/block_events must be in [0, {STAMP_STRIDE - 2}] "
            f"(stamp-stride limit): {value}")
    return value


# Upper bound on tpu/miss_chain: the chain replay is a fori_loop of P
# per-slot phases inside ONE resolve pass, so P is a direct multiplier
# on per-pass device work — past the low hundreds the pass stops being
# "a round" in any honest sense, and the [P, T] bank arrays start to
# rival the caches.  Banking depth beyond a window's miss yield per
# sub-round (~block_events) buys nothing anyway: the chain cadence
# serves every sub-round.
MISS_CHAIN_MAX = 256


def _miss_chain(value: int) -> int:
    if not 0 <= value <= MISS_CHAIN_MAX:
        raise ConfigError(
            f"tpu/miss_chain must be in [0, {MISS_CHAIN_MAX}]: {value}")
    return value


# Upper bound on tpu/fast_forward (span width, in block_events-sized
# windows): an engaged fast-forward round prices its whole span under ONE
# round_ctr value, so the span's per-event stamp offsets must fit the
# round's exclusive STAMP_STRIDE allocation (the effective span is also
# clipped to the resident window-cache width, 4 windows — see
# engine/core._ff_width).  Values past STRIDE buy nothing.
FAST_FORWARD_MAX = STAMP_STRIDE


def _fast_forward(value: int) -> int:
    if not 0 <= value <= FAST_FORWARD_MAX:
        raise ConfigError(
            f"tpu/fast_forward must be in [0, {FAST_FORWARD_MAX}]: "
            f"{value}")
    return value


_PALLAS_KERNEL_MODES = ("auto", "off", "interpret", "on")


def _pallas_kernels(value: str) -> str:
    if value not in _PALLAS_KERNEL_MODES:
        raise ConfigError(
            f"tpu/pallas_kernels must be one of {_PALLAS_KERNEL_MODES}: "
            f"{value!r}")
    return value


_SHARD_STATE_MODES = ("replicated", "resident")


def _shard_state(value: str) -> str:
    if value not in _SHARD_STATE_MODES:
        raise ConfigError(
            f"tpu/shard_state must be one of {_SHARD_STATE_MODES}: "
            f"{value!r}")
    return value


def _tile_shards(raw: str, num_tiles: int) -> int:
    """Resolve ``tpu/tile_shards`` to a concrete shard count.

    ``"auto"`` takes the largest divisor of the tile count that the
    attached device set can carry (1 on a single device — today's
    program); an explicit integer must divide ``num_tiles`` evenly and
    fit the device count, because shard_map splits the tile axis into
    equal per-device blocks.  The resolved value is STATIC: it selects
    the compiled program (sharded vs single-device), so it lives in
    SimParams like ``pallas_kernels`` rather than in a runtime flag.
    """
    if raw == "auto":
        import jax
        d = jax.local_device_count()
        s = max(v for v in range(1, d + 1) if num_tiles % v == 0)
        return s
    try:
        value = int(raw)
    except ValueError:
        raise ConfigError(
            f"tpu/tile_shards must be 'auto' or a positive integer: "
            f"{raw!r}")
    if value < 1:
        raise ConfigError(f"tpu/tile_shards must be >= 1: {value}")
    if num_tiles % value:
        raise ConfigError(
            f"tpu/tile_shards={value} must divide the tile count "
            f"{num_tiles} (shard_map splits the tile axis into equal "
            f"per-device blocks)")
    return value


def _syscall_costs(cfg: Config) -> tuple:
    """[syscall] per-class service cycles, ordered by isa.SyscallClass."""
    from graphite_tpu.isa import SyscallClass
    return tuple(
        cfg.get_int(f"syscall/{c.name.lower()}_cost")
        for c in SyscallClass)


def _ceil_pow2(x: int) -> int:
    return 1 << _ceil_log2(x)


@dataclasses.dataclass(frozen=True)
class CacheParams:
    """Geometry + latency for one set-associative cache level
    (reference: common/tile/memory_subsystem/cache/cache.h:26-80 and the
    [l1_icache/*]/[l1_dcache/*]/[l2_cache/*] sections)."""

    name: str
    line_size: int          # bytes
    size_kb: int
    associativity: int
    num_banks: int
    replacement: str        # 'lru' | 'round_robin'
    data_access_cycles: int
    tags_access_cycles: int
    perf_model: str         # 'parallel' | 'sequential'
    track_miss_types: bool

    @property
    def num_sets(self) -> int:
        sets = (self.size_kb * 1024) // (self.line_size * self.associativity)
        if sets * self.line_size * self.associativity != self.size_kb * 1024:
            raise ConfigError(f"{self.name}: size not divisible into sets")
        return sets

    @property
    def set_bits(self) -> int:
        sets = self.num_sets
        if sets & (sets - 1):
            raise ConfigError(f"{self.name}: num_sets {sets} not a power of 2")
        return sets.bit_length() - 1

    @property
    def access_cycles(self) -> int:
        """Hit latency: parallel tag+data lookup takes max(), sequential
        takes the sum (reference: cache_perf_model_parallel.h /
        cache_perf_model_sequential.h)."""
        if self.perf_model == "parallel":
            return max(self.data_access_cycles, self.tags_access_cycles)
        return self.data_access_cycles + self.tags_access_cycles

    @classmethod
    def from_config(cls, cfg: Config, section: str, name: str) -> "CacheParams":
        g = lambda k: f"{section}/{k}"
        return cls(
            name=name,
            line_size=cfg.get_int(g("cache_line_size")),
            size_kb=cfg.get_int(g("cache_size")),
            associativity=cfg.get_int(g("associativity")),
            num_banks=cfg.get_int(g("num_banks")),
            replacement=cfg.get_str(g("replacement_policy")),
            data_access_cycles=cfg.get_int(g("data_access_time")),
            tags_access_cycles=cfg.get_int(g("tags_access_time")),
            perf_model=cfg.get_str(g("perf_model_type")),
            track_miss_types=cfg.get_bool(g("track_miss_types")),
        )


@dataclasses.dataclass(frozen=True)
class DirectoryParams:
    """DRAM-directory geometry (reference: [dram_directory] section;
    auto-sizing semantics of
    common/tile/memory_subsystem/cache/directory_cache.cc:243-330)."""

    total_entries: int
    associativity: int
    max_hw_sharers: int
    directory_type: str     # full_map | limited_broadcast | limited_no_broadcast | ackwise | limitless
    access_cycles: int
    limitless_trap_cycles: int
    # Ack-combining cost (cycles) the directory pays per invalidation
    # round: the INV round trip completes when the LAST sharer's ack has
    # been folded in (reference dram_directory_cntlr counts acks and
    # unblocks on the final one).  Default 1 keeps the pre-round-9
    # math (one requester-core cycle on top of the max-hop round trip).
    inv_ack_cycles: int = 1

    @property
    def num_sets(self) -> int:
        return self.total_entries // self.associativity

    @classmethod
    def from_config(cls, cfg: Config, num_tiles: int, l2: CacheParams,
                    num_slices: int) -> "DirectoryParams":
        assoc = cfg.get_int("dram_directory/associativity")
        total_entries = _int_or_keyword(cfg, "dram_directory/total_entries", "auto")
        if total_entries is None:
            # Cover 2x the aggregate L2 capacity, spread over the directory
            # slices, rounded up to a power-of-2 set count (same sizing rule
            # as the reference, directory_cache.cc:249-256).
            sets = math.ceil(2.0 * l2.size_kb * 1024 * num_tiles /
                             (l2.line_size * assoc * num_slices))
            sets = _ceil_pow2(sets)
            total_entries = sets * assoc

        access = _int_or_keyword(cfg, "dram_directory/access_time", "auto")
        if access is None:
            access = _auto_directory_access_cycles(
                total_entries, num_tiles, cfg.get_int("dram_directory/max_hw_sharers"))

        return cls(
            total_entries=total_entries,
            associativity=assoc,
            max_hw_sharers=cfg.get_int("dram_directory/max_hw_sharers"),
            directory_type=cfg.get_str("dram_directory/directory_type"),
            access_cycles=access,
            limitless_trap_cycles=cfg.get_int("limitless/software_trap_penalty"),
            inv_ack_cycles=_positive(
                cfg.get_int("dram_directory/inv_ack_combining_cycles", 1),
                "dram_directory/inv_ack_combining_cycles"),
        )


def _auto_directory_access_cycles(total_entries: int, num_tiles: int,
                                  max_hw_sharers: int) -> int:
    """Size-binned access latency, as in the reference's auto table
    (directory_cache.cc:300-322): bigger structure -> more cycles."""
    # Entry size ~ state byte + sharer bitmap over the tracked sharers.
    entry_bytes = 1 + max(4, max_hw_sharers // 8)
    size_kb = math.ceil(total_entries * entry_bytes / 1024)
    for bound, cycles in ((16, 1), (32, 2), (64, 4), (128, 6), (256, 8),
                          (512, 10), (1024, 13), (2048, 16)):
        if size_kb <= bound:
            return cycles
    return 20


QUEUE_MODEL_TYPES = ("basic", "history_list", "history_tree", "m_g_1")
_QUEUE_MODEL_TYPES = QUEUE_MODEL_TYPES


def _queue_model_type(val: str, key: str) -> str:
    """Queue-model selection fails loudly on unknown types, matching the
    reference factory (QueueModel::create, queue_model.cc:18-37 —
    LOG_PRINT_ERROR on anything it doesn't know).  ``m_g_1`` is accepted
    directly (the reference embeds it inside history_tree;
    queue_model_m_g_1.cc is its own class)."""
    if val not in _QUEUE_MODEL_TYPES:
        raise ConfigError(
            f"{key} = {val!r}: unknown queue model (valid: "
            f"{', '.join(_QUEUE_MODEL_TYPES)})")
    return val


def _basic_ma_window(cfg: Config) -> int:
    """[queue_model/basic] moving-average window (reference
    queue_model_basic.cc:14-31): 0 when disabled; only arithmetic_mean
    is implemented — other averagers fail loudly."""
    if not cfg.get_bool("queue_model/basic/moving_avg_enabled", False):
        return 0
    ma_type = cfg.get_str("queue_model/basic/moving_avg_type",
                          "arithmetic_mean")
    if ma_type != "arithmetic_mean":
        raise ConfigError(
            f"queue_model/basic/moving_avg_type = {ma_type!r} is not "
            f"implemented (supported: arithmetic_mean)")
    w = cfg.get_int("queue_model/basic/moving_avg_window_size", 1)
    if w <= 0:
        raise ConfigError(
            f"queue_model/basic/moving_avg_window_size must be positive, "
            f"got {w}")
    return w


def _link_queue_model_type(val: str, key: str) -> str:
    if val not in ("basic", "history_list", "history_tree"):
        raise ConfigError(
            f"{key} = {val!r}: unknown link queue model (valid: basic, "
            f"history_list, history_tree — the reference factory's set, "
            f"queue_model.cc:18-37)")
    return val


@dataclasses.dataclass(frozen=True)
class DramParams:
    """DRAM controller timing (reference: [dram] section;
    dram_perf_model.h:19-60 latency = access cost + size/bandwidth +
    queueing delay)."""

    latency_ns: float
    per_controller_bandwidth_gbps: float
    num_controllers: int          # resolved count (ALL -> num_tiles)
    controller_home_stride: int   # tiles between successive controllers
    queue_model_enabled: bool
    queue_model_type: str
    # [queue_model/basic] moving average: effective window size, 0 when
    # disabled (reference queue_model_basic.cc reads moving_avg_enabled/
    # window_size/type; only arithmetic_mean is implemented here).
    basic_ma_window: int = 0

    @property
    def latency_ps(self) -> int:
        return int(ns_to_ps(self.latency_ns))

    def processing_ps_per_line(self, line_size: int) -> int:
        # bytes / (GB/s) = ns; serialization cost per cache line.
        return int(round(line_size / self.per_controller_bandwidth_gbps * 1000))

    @classmethod
    def from_config(cls, cfg: Config, num_tiles: int) -> "DramParams":
        n = _int_or_keyword(cfg, "dram/num_controllers", "ALL")
        if n is None:
            n = num_tiles
        elif n <= 0 or n > num_tiles:
            raise ConfigError(f"dram/num_controllers out of range: {n}")
        stride = max(1, num_tiles // n)
        return cls(
            latency_ns=cfg.get_float("dram/latency"),
            per_controller_bandwidth_gbps=cfg.get_float("dram/per_controller_bandwidth"),
            num_controllers=n,
            controller_home_stride=stride,
            queue_model_enabled=cfg.get_bool("dram/queue_model/enabled"),
            queue_model_type=_queue_model_type(
                cfg.get_str("dram/queue_model/type"), "dram/queue_model/type"),
            basic_ma_window=_basic_ma_window(cfg),
        )


def _telemetry_interval_ns(cfg: Config) -> int:
    """[telemetry] interval contribution to the shared sampling cadence
    (ns; 1<<40 = no contribution).  The default 'auto' RIDES whatever
    cadence the statistics/progress/power rings already configured —
    turning telemetry on must not retime or early-saturate the traces
    the user explicitly asked for — and falls back to 10 us when
    telemetry is the only sampler.  An explicit integer participates in
    the shared min like any other sampler."""
    if not cfg.get_bool("telemetry/enabled", False):
        return 1 << 40
    if not cfg.has("telemetry/interval"):
        val = None
    else:
        val = _int_or_keyword(cfg, "telemetry/interval", "auto")
    if val is None:     # auto
        others_on = (cfg.get_bool("statistics_trace/enabled")
                     or cfg.get_bool("progress_trace/enabled")
                     or cfg.get_bool(
                         "runtime_energy_modeling/power_trace/enabled",
                         False))
        return (1 << 40) if others_on else 10000
    # 0 would reach _maybe_sample's `boundary // interval` as a jitted
    # divide-by-zero (implementation-defined on device, no exception).
    return _positive(val, "telemetry/interval")


def pow2_grid(n: int, tall: bool) -> Tuple[int, int]:
    """Factor a power-of-two count onto a grid (reference
    initializeClusters / sub-cluster math, network_model_atac.cc:594-630):
    even log2 -> square; odd -> 2:1, long side on Y when ``tall`` (the
    cluster grid) or on X otherwise (the sub-cluster grid)."""
    lg = n.bit_length() - 1
    assert n == 1 << lg
    if lg % 2 == 0:
        return 1 << (lg // 2), 1 << (lg // 2)
    lo, hi = 1 << ((lg - 1) // 2), 1 << ((lg + 1) // 2)
    return (lo, hi) if tall else (hi, lo)


@dataclasses.dataclass(frozen=True)
class AtacParams:
    """ATAC hybrid optical-broadcast network geometry + delays
    (reference: network_model_atac.{h,cc}, [network/atac]
    carbon_sim.cfg:315-352).  All fields are scalars so SimParams stays
    hashable (jit static arg); per-tile tables derive from these in
    engine/noc_atac.py.
    """

    num_tiles: int
    enet_width: int
    enet_height: int
    cluster_size: int
    num_clusters: int
    numx_clusters: int
    numy_clusters: int
    cluster_width: int
    cluster_height: int
    num_access_points: int            # per cluster
    receive_net_type: str             # star | htree
    global_routing_strategy: str      # cluster_based | distance_based
    unicast_distance_threshold: int
    send_hub_router_delay: int        # cycles
    receive_hub_router_delay: int     # cycles
    star_net_router_delay: int        # cycles
    optical_link_delay_cycles: int    # EO + waveguide + OE, at init freq

    @classmethod
    def from_config(cls, cfg: Config, num_tiles: int,
                    net_freq_ghz: float) -> "AtacParams":
        # ENet sizing per the reference (isTileCountPermissible,
        # network_model_atac.cc:844-856 — same rule as the electrical
        # mesh): w = floor(sqrt(T)), h = ceil(T/w), T must fill the grid.
        w = int(math.floor(math.sqrt(num_tiles)))
        h = int(math.ceil(num_tiles / w))
        if num_tiles != w * h:
            raise ConfigError(
                f"network/atac: can't form a mesh with tile count "
                f"{num_tiles} (reference isTileCountPermissible)")
        csize = cfg.get_int("network/atac/cluster_size", 4)
        if csize <= 0 or num_tiles % csize:
            raise ConfigError(
                f"network/atac/cluster_size = {csize} must divide the "
                f"tile count {num_tiles}")
        nclust = num_tiles // csize
        # Cluster grid factorization (reference initializeClusters,
        # network_model_atac.cc:594-618).  The reference's sqrt math
        # silently assumes a power-of-two cluster count; here that
        # assumption is a loud check.
        if nclust != 1 << (nclust.bit_length() - 1):
            raise ConfigError(
                f"network/atac: cluster count {nclust} must be a power "
                f"of two (reference initializeClusters sqrt math)")
        nx, ny = pow2_grid(nclust, tall=True)
        cw, ch = w // nx, h // ny
        if cw * nx != w or ch * ny != h:
            raise ConfigError(
                f"network/atac: cluster grid {nx}x{ny} does not tile the "
                f"{w}x{h} ENet evenly")
        # Optical waveguide length (mm) per the reference's cases
        # (computeOpticalLinkLength, network_model_atac.cc:560-585).
        tile_w = cfg.get_float("general/tile_width", 1.0)
        if nclust == 2:
            length = ch * tile_w
        elif nclust == 4:
            length = (cw * tile_w) * (ch * tile_w)
        elif nclust == 8:
            length = (cw * tile_w) * (2 * ch * tile_w)
        else:
            rect_l = (nx - 2) * cw * tile_w
            rect_h = (ch * 2) * tile_w
            length = max(ny // 4, 1) * 2 * (rect_l + rect_h)
        wg_ns_per_mm = cfg.get_float(
            "link_model/optical/waveguide_delay_per_mm", 10e-3)
        eo = cfg.get_int("link_model/optical/E-O_conversion_delay", 1)
        oe = cfg.get_int("link_model/optical/O-E_conversion_delay", 1)
        # Cycle count fixed at the network's initial frequency, as the
        # reference computes it once at init (optical_link_model.cc:51-54).
        optical_cycles = int(math.ceil(
            wg_ns_per_mm * length * net_freq_ghz + eo + oe))
        rnet = cfg.get_str("network/atac/receive_network_type", "star")
        if rnet not in ("star", "btree"):
            raise ConfigError(
                f"network/atac/receive_network_type = {rnet!r} "
                f"(valid: star, btree — reference parseReceiveNetType)")
        strat = cfg.get_str("network/atac/global_routing_strategy",
                            "cluster_based")
        if strat not in ("cluster_based", "distance_based"):
            raise ConfigError(
                f"network/atac/global_routing_strategy = {strat!r} "
                f"(valid: cluster_based, distance_based)")
        return cls(
            num_tiles=num_tiles, enet_width=w, enet_height=h,
            cluster_size=csize, num_clusters=nclust,
            numx_clusters=nx, numy_clusters=ny,
            cluster_width=cw, cluster_height=ch,
            num_access_points=cfg.get_int(
                "network/atac/num_optical_access_points_per_cluster", 4),
            receive_net_type=rnet,
            global_routing_strategy=strat,
            unicast_distance_threshold=cfg.get_int(
                "network/atac/unicast_distance_threshold", 4),
            send_hub_router_delay=cfg.get_int(
                "network/atac/onet/send_hub/router/delay", 1),
            receive_hub_router_delay=cfg.get_int(
                "network/atac/onet/receive_hub/router/delay", 1),
            star_net_router_delay=cfg.get_int(
                "network/atac/star_net/router/delay", 1),
            optical_link_delay_cycles=optical_cycles,
        )


@dataclasses.dataclass(frozen=True)
class NetworkParams:
    """One logical network's model selection + constants (reference:
    [network] + per-model sections; models enumerated in
    common/network/network_model.h and common/network/models/)."""

    model: str                 # magic | emesh_hop_counter | emesh_hop_by_hop | atac
    flit_width_bits: int
    router_delay_cycles: int
    link_delay_cycles: int
    queue_model_enabled: bool
    queue_model_type: str
    broadcast_tree_enabled: bool
    atac: Optional[AtacParams] = None

    @classmethod
    def from_config(cls, cfg: Config, which: str, num_tiles: int,
                    net_freq_ghz: float) -> "NetworkParams":
        model = cfg.get_str(f"network/{which}")
        sec = f"network/{model}"
        if model == "magic":
            return cls(model, 64, 0, 0, False, "none", False)
        atac = None
        if model == "atac":
            atac = AtacParams.from_config(cfg, num_tiles, net_freq_ghz)
        return cls(
            model=model,
            flit_width_bits=cfg.get_int(f"{sec}/flit_width", 64),
            # ATAC's electrical mesh (ENet) reuses the emesh router/link
            # delays ("ENet is modeled similar to an electrical mesh",
            # carbon_sim.cfg:331).
            router_delay_cycles=cfg.get_int(
                f"{sec}/enet/router/delay" if model == "atac"
                else f"{sec}/router/delay", 1),
            link_delay_cycles=cfg.get_int(f"{sec}/link/delay", 1),
            queue_model_enabled=cfg.get_bool(f"{sec}/queue_model/enabled", False),
            # Link queues accept the reference factory's three types
            # (basic/history_list/history_tree — queue_model.cc:18-37;
            # m_g_1 is DRAM-only here, as in the reference where it only
            # exists inside history_tree).  All three map to the exact
            # per-link FCFS sweep (noc_flight.py) — exact FCFS == basic
            # for in-order arrivals and >= history fidelity otherwise.
            queue_model_type=_link_queue_model_type(
                cfg.get_str(f"{sec}/queue_model/type", "history_tree"),
                f"{sec}/queue_model/type"),
            broadcast_tree_enabled=cfg.get_bool(f"{sec}/broadcast_tree_enabled", False),
            atac=atac,
        )


@dataclasses.dataclass(frozen=True)
class CoreParams:
    """Core model selection + static costs (reference: [tile]/model_list,
    [core/static_instruction_costs], [branch_predictor],
    core model registry common/tile/core/core_model.cc:15)."""

    model: str                    # 'simple' | 'iocoom'
    static_costs: Tuple[int, ...]  # indexed by InstructionType order
    bp_type: str
    bp_size: int
    bp_mispredict_penalty: int
    # iocoom knobs (reference: [core/iocoom], carbon_sim.cfg:180-186)
    load_queue_entries: int
    store_queue_entries: int
    speculative_loads: bool
    multiple_outstanding_rfos: bool
    # Heterogeneous [tile]/model_list (reference carbon_sim.cfg:158-176,
    # config.cc:365-460): per-tile True where the tile runs the iocoom
    # model.  None = homogeneous (every tile is ``model``); when set,
    # ``model`` is "iocoom" so the engine allocates the LQ/SQ/scoreboard
    # state, and the per-tile mask gates its semantics.
    iocoom_mask: Optional[Tuple[bool, ...]] = None

    @property
    def mixed(self) -> bool:
        return self.iocoom_mask is not None

    @classmethod
    def from_config(cls, cfg: Config, core_type: str,
                    iocoom_mask: Optional[Tuple[bool, ...]] = None
                    ) -> "CoreParams":
        costs = tuple(
            cfg.get_int(f"core/static_instruction_costs/{t.config_key}")
            for t in STATIC_COST_TYPES
        )
        return cls(
            model=core_type,
            static_costs=costs,
            iocoom_mask=iocoom_mask,
            bp_type=cfg.get_str("branch_predictor/type"),
            bp_size=cfg.get_int("branch_predictor/size"),
            bp_mispredict_penalty=cfg.get_int("branch_predictor/mispredict_penalty"),
            load_queue_entries=cfg.get_int("core/iocoom/num_load_queue_entries"),
            store_queue_entries=cfg.get_int("core/iocoom/num_store_queue_entries"),
            speculative_loads=cfg.get_bool("core/iocoom/speculative_loads_enabled"),
            multiple_outstanding_rfos=cfg.get_bool("core/iocoom/multiple_outstanding_RFOs_enabled"),
        )


_MODEL_LIST_RE = re.compile(r"<([^>]*)>")


def parse_tile_model_list(raw: str) -> Tuple[Tuple[str, str, str, str, str], ...]:
    """Parse [tile]/model_list tuples
    ``<count, core-type, l1i, l1d, l2>`` (reference: carbon_sim.cfg:158-176)."""
    tuples = []
    for m in _MODEL_LIST_RE.finditer(raw):
        fields = [f.strip() for f in m.group(1).split(",")]
        if len(fields) != 5:
            raise ConfigError(f"bad tile model tuple: <{m.group(1)}>")
        tuples.append(tuple(fields))
    if not tuples:
        raise ConfigError(f"no tile model tuples in {raw!r}")
    return tuple(tuples)


def parse_dvfs_domains(raw: str) -> Tuple[Tuple[float, Tuple[int, ...]], ...]:
    """Parse [dvfs]/domains ``<freq, MODULE, ...>`` tuples into
    (freq_ghz, module-ids) pairs (reference: carbon_sim.cfg:147-151,
    dvfs_manager.h:19-88)."""
    domains = []
    for m in _MODEL_LIST_RE.finditer(raw):
        fields = [f.strip() for f in m.group(1).split(",") if f.strip()]
        try:
            freq = float(fields[0])
            modules = tuple(int(DVFSModule.parse(f)) for f in fields[1:])
        except (IndexError, ValueError, KeyError):
            raise ConfigError(f"bad dvfs domain tuple: <{m.group(1)}>") from None
        domains.append((freq, modules))
    if not domains:
        raise ConfigError(f"no dvfs domains in {raw!r}")
    return tuple(domains)


@dataclasses.dataclass(frozen=True)
class SimParams:
    """All static parameters of one simulation run."""

    num_tiles: int
    mesh_width: int
    mesh_height: int
    max_frequency_ghz: float
    quantum_ps: int
    clock_skew_scheme: str

    # ThreadScheduler (reference: common/system/thread_scheduler.h:30-56 +
    # round_robin_thread_scheduler.cc): how many app-thread streams may
    # queue on one tile (the reference's general/max_threads_per_core
    # knob, config.cc:48), and the preemption quantum after which a
    # seated stream rotates out round-robin.  The reference measures its
    # switch quantum in HOST seconds (thread_scheduler.cc:632-636,
    # time(NULL)); here it is SIMULATED time — deterministic and
    # host-independent, the TPU engine's native clock.
    max_threads_per_core: int
    thread_switch_quantum_ps: int

    core: CoreParams
    l1i: CacheParams
    l1d: CacheParams
    l2: CacheParams
    protocol: str
    l2_directory_type: str
    l2_max_hw_sharers: int
    directory: DirectoryParams
    dram: DramParams
    net_user: NetworkParams
    net_memory: NetworkParams

    dvfs_domains: Tuple[Tuple[float, Tuple[int, ...]], ...]
    dvfs_sync_delay_cycles: int
    # Miss-type classification ([cache]/track_miss_types on the L1D or L2;
    # reference cache.h:45-49): resolve classifies every served miss as
    # cold / capacity / sharing through per-tile line filters.
    track_miss_types: bool
    # Per-class syscall service cycles at the MCP's syscall server, indexed
    # by isa.SyscallClass (reference: syscall_server.cc executes the host
    # call and charges marshalling round trips; the service table is this
    # rebuild's analytic stand-in for host-execution time, [syscall] in
    # defaults.cfg).
    syscall_cost_cycles: tuple

    # Simulated address-space layout (reference: vm_manager.cc reads
    # [stack] stack_base / stack_size_per_core, carbon_sim.cfg:113-117;
    # engine/vm.py).
    stack_base: int
    stack_size_per_core: int

    enable_core_modeling: bool
    enable_power_modeling: bool
    technology_node: int

    # Region-of-interest: initial models-enabled flag (reference:
    # [general]/trigger_models_within_application + Simulator::
    # enableModels, simulator.cc:287-301) — when triggering within the
    # application, timing models stay off until an ENABLE_MODELS event.
    models_enabled_at_start: bool

    # Periodic sampling (reference: StatisticsManager barrier-clocked
    # sampling statistics_manager.cc:41-114 + pin/progress_trace.cc).
    stats_enabled: bool
    progress_enabled: bool
    stat_interval_ps: int
    max_stat_samples: int
    # Periodic power trace ([runtime_energy_modeling/power_trace],
    # reference carbon_sim.cfg:141-145 + TileEnergyMonitor): sample the
    # energy-bearing counters every [runtime_energy_modeling] interval
    # and derive per-interval power (energy.power_trace).
    power_trace_enabled: bool
    # [telemetry] engine-health round metrics (obs/metrics.TEL_SERIES):
    # sampled at quantum boundaries through the SAME _maybe_sample hook
    # as the statistics/progress/power rings (its interval folds into
    # stat_interval_ps), so enabling telemetry adds no fused-loop
    # branches; disabling it allocates no sample arrays.
    telemetry_enabled: bool

    # TPU engine knobs
    # Window width of the block-retirement fast path (events gathered per
    # tile per local round; 0 disables it — every event then goes through
    # the general one-event slot, the round-2 engine shape).
    block_events: int
    # Quantum-scoped block-window cache: gather the window's trace slice
    # into resident [T, 4K] SimState arrays that advance with the cursor,
    # instead of re-gathering [T, K] from the full device trace every
    # round (engine/core._block_retire; PROFILE.md lever 2).  Results are
    # bit-identical either way — false restores the per-round gather (the
    # round-identity oracle in tests/test_block_equivalence.py).
    window_cache: bool
    max_events_per_quantum: int
    directory_conflict_rounds: int
    rounds_per_quantum: int
    quanta_per_step: int
    # Max invalidation fan-outs (EX-on-S invalidation sets + shared-victim
    # directory evictions) delivered per conflict round; requests beyond the
    # budget defer to the next round (counted in dir_deferrals).  Bounds the
    # per-round invalidation scatter at [budget, T] instead of [T, T].
    max_inv_fanout_per_round: int
    # Miss-chain banking depth (the round-4 perf design): the block window
    # keeps executing past L2 misses WITHOUT installing them (blocking
    # semantics, stall-on-use), banking up to this many pending requests
    # per tile; each resolve pass replays banked chains sequentially
    # inside one engine round (element k+1 is priced against the
    # post-element-k directory state; its issue is element k's
    # completion plus the recorded local delta), so a tile costs ~1
    # device round per CHAIN instead of one per miss.  Gated at 2%
    # completion parity against the oracle (tests/
    # test_chain_equivalence.py).  0 restores the round-3
    # one-parked-request engine (the equivalence oracle) bit-exactly.
    miss_chain: int
    # Upper bound on one-element-per-round conflict rounds per resolve
    # pass (the fan-out/live-victim fallback after the chain replay);
    # leftovers carry to the next sub-round's pass via mq_head.
    max_resolve_rounds: int
    # Round-9 chain cadence (effective only with miss_chain > 0): serve
    # invalidation fan-outs INSIDE the chain replay (batched per-sharer
    # INV pricing instead of demoting the whole chain to the
    # one-element-per-round fallback), let the block window span the
    # quantum boundary by one quantum instead of truncating mid-window,
    # and advance the barrier past served chain progress.  False
    # restores the round-8 chain engine — the bench fft64 A/B switch.
    fanout_replay: bool
    # Round-10 Pallas round-cost kernels (engine/kernels/): run the block
    # window's K-deep walk and the chain replay's classify/elect/combine
    # phase as fused TPU kernels over VMEM-resident operands instead of
    # dozens of sequentially dispatched XLA ops.  A STRING so the sweep
    # zoo classifies it structural by nature:
    #   "auto"      — real Pallas on a TPU backend, plain lax elsewhere
    #   "off"       — always the lax reference path
    #   "interpret" — Pallas kernels under the interpreter (CPU-testable;
    #                 the bit-identity gate in tests/test_kernels.py)
    #   "on"        — force real Pallas lowering (TPU only)
    # Results are bit-identical across all values — the kernels run the
    # SAME walk/classify code on block-sliced operands (all-integer
    # arithmetic; per-tile independent), dispatched in kernels/dispatch.
    pallas_kernels: str
    # Round-11 explicit tile-axis sharding (parallel/mesh.py): the
    # RESOLVED shard count of the quantum step's shard_map over the
    # device mesh.  1 is today's single-device program, bit for bit
    # (no shard_map wrapper is applied at all); S > 1 runs the block
    # window's walk on T/S tiles per device (sliced operands, outputs
    # all_gathered back) with the quantum barrier as an explicit pmin
    # collective, everything else replicated.  Bit-identical across
    # values — the gate in tests/test_sharding.py.  Config accepts
    # "auto" (largest divisor of T the device set carries) or an
    # explicit divisor of T; the field always holds the resolved int.
    tile_shards: int
    # Round-15 resident sharding (engine/resident.py): "replicated" is
    # the round-11 program above — state replicated on every device, the
    # hot phase shard_mapped, outputs all_gathered back each step.
    # "resident" keeps every T-leading SimState leaf SHARDED along the
    # tile axis for the whole run: the window walk and local advance run
    # shard-local with no output gathers, and the resolve/chain phase is
    # re-expressed as home-binned routing (chain heads bucketed by
    # dense.home_fold home shard, all_to_all-routed to their home
    # device, priced against home-resident directory state, routed
    # back).  Per-device resident HBM drops from O(T) to O(T/S) and the
    # 13 per-step all_gathers become <=2 fixed-capacity all_to_alls
    # plus the existing pmin barrier.  The resident program is its own
    # family: its contract is shard-count invariance (resident S=8 ==
    # resident S=1, bit for bit), checked in tests/test_sharding.py.
    # Only a validated config subset lowers (engine/resident.py
    # validate_params); anything else raises ConfigError up front.
    shard_state: str
    # Per-(source shard, dest shard) record capacity of the resident
    # routing all_to_all.  0 ("auto") sizes it at 2*T/S — structurally
    # never overflowing; smaller explicit values shrink the routed
    # payload, and a step whose inbound heads exceed the budget takes
    # the host-side overflow spill (value-identical, counted in
    # obs routing_overflows_total) so correctness never depends on the
    # heuristic.
    route_capacity: int
    channel_depth: int
    # Captured-trace replay: a recorded COND_WAIT provably consumed SOME
    # signal in the native run, but simulated retiming can invert the
    # recorded wait/signal pair; replay mode wakes waiters on any
    # outstanding token at max(park, token time) instead of enforcing
    # strict lost-signal eligibility (engine/resolve.resolve_cond).
    cond_replay: bool
    # Round-12 adaptive-fidelity fast-forward (engine/core.py + the
    # kernels/window.fast_forward_walk leg): before each detailed
    # sub-round, detect tiles whose next events are ALL hit/compute —
    # no bankable misses, no sync ops, no pending chain heads — and
    # price the longest such prefix of the resident window in closed
    # form (cumulative clock advance + bulk counter accumulation +
    # batched LRU touches) instead of iterating window rounds.  The
    # value is the span width in block_events-sized windows (clipped to
    # the resident cache's 4 windows and the stamp stride); 0 compiles
    # the fast-forward leg out entirely — bit-identical to the
    # pre-round-12 engine (tests/data/fast_forward_golden.json).
    fast_forward: int
    # Fast-forward accuracy budget, picoseconds (config key
    # tpu/fast_forward_span is in NANOSECONDS): eligible tiles may
    # commit analytic progress up to this far PAST the quantum boundary,
    # trading barrier fidelity for fewer quanta, the same knob class as
    # Graphite's lax synchronization.  0 (the default) keeps the exact
    # quantum barrier.  VARIANT in the sweep zoo — a traced operand
    # (vparams.py), so sweeps get a cost/accuracy axis without
    # recompiling.
    fast_forward_span_ps: int
    # Round-16 streaming segmented ingest (engine/ingest.py, config key
    # [trace] segment_events): 0 uploads the whole trace at startup —
    # today's program, bit for bit.  > 0 keeps only a [T, segment_events]
    # RESIDENT SEGMENT of the event stream on device (plus one prefetch
    # buffer uploading the predicted next window while the megarun
    # runs), bounding device trace memory at O(segment) for any trace
    # length; the streamed walk is bit-identical to the whole-trace
    # program (speculative quantum + rollback at segment overruns —
    # ingest.py's contract).  Must be >= 2x the engine's read lookahead
    # (``ingest_lookahead``); the not-yet-validated combinations
    # (resident shard_state, fast_forward, multi-thread scheduling)
    # reject loudly in __post_init__ / engine/ingest.validate_streaming.
    segment_events: int

    @property
    def ingest_lookahead(self) -> int:
        """Max events past the cursor one engine round may READ (the
        window-cache refresh gathers the full [T, WC] resident span):
        the streaming overrun guard's per-row lookahead.  Whole-trace
        runs never use it."""
        K = self.block_events
        if K <= 0:
            return 1
        if self.window_cache:
            return 4 * K     # state._win_cache_width's geometry
        return K

    @property
    def line_size(self) -> int:
        return self.l2.line_size

    @property
    def shared_l2(self) -> bool:
        """True for the shared-distributed-L2 protocols: the directory
        arrays ARE the per-tile L2 slices (directory integrated in L2,
        reference pr_l1_sh_l2_msi/l2_cache_cntlr.cc + l2_directory_cfg.cc),
        and there is no private L2."""
        return self.protocol.startswith("pr_l1_sh_l2")

    @property
    def protocol_kind(self) -> str:
        """Directory FSM family: 'msi' | 'mosi' | 'sh_l2_msi' | 'sh_l2_mesi'."""
        return {
            "pr_l1_pr_l2_dram_directory_msi": "msi",
            "pr_l1_pr_l2_dram_directory_mosi": "mosi",
            "pr_l1_sh_l2_msi": "sh_l2_msi",
            "pr_l1_sh_l2_mesi": "sh_l2_mesi",
        }[self.protocol]

    def __post_init__(self):
        sizes = {self.l1i.line_size, self.l1d.line_size, self.l2.line_size}
        if len(sizes) != 1:
            raise ConfigError(
                f"cache line sizes must agree across L1I/L1D/L2, got {sizes}")
        # A config-compatible simulator that quietly simulates a different
        # machine is worse than one that refuses: every selectable model
        # variant that the engine does not implement yet fails loudly here
        # instead of silently running the implemented one.
        def _check(what, value, supported):
            if value not in supported:
                raise ConfigError(
                    f"{what} '{value}' is not implemented "
                    f"(supported: {sorted(supported)})")
        _check("tile core model", self.core.model, {"simple", "iocoom"})
        if self.core.model == "iocoom":
            _positive(self.core.load_queue_entries,
                      "core/iocoom/num_load_queue_entries")
            _positive(self.core.store_queue_entries,
                      "core/iocoom/num_store_queue_entries")
        _check("caching_protocol/type", self.protocol,
               {"pr_l1_pr_l2_dram_directory_msi",
                "pr_l1_pr_l2_dram_directory_mosi",
                "pr_l1_sh_l2_msi", "pr_l1_sh_l2_mesi"})
        # Validate the OPERATIVE scheme field (directory.directory_type is
        # what the engine reads; it is sourced from [l2_directory] under
        # shared L2 and [dram_directory] otherwise).
        _schemes = {"full_map", "limited_broadcast", "limited_no_broadcast",
                    "ackwise", "limitless"}
        _check("l2_directory/directory_type" if self.shared_l2
               else "dram_directory/directory_type",
               self.directory.directory_type, _schemes)
        if self.directory.directory_type != "full_map":
            _positive(self.directory.max_hw_sharers,
                      "directory max_hw_sharers")
        if self.enable_power_modeling:
            from graphite_tpu.energy import DVFS_LEVELS
            _check("general/technology_node", self.technology_node,
                   set(DVFS_LEVELS))
        _check("network/user model", self.net_user.model,
               {"magic", "emesh_hop_counter", "emesh_hop_by_hop", "atac"})
        _check("network/memory model", self.net_memory.model,
               {"magic", "emesh_hop_counter", "emesh_hop_by_hop", "atac"})
        _check("branch_predictor/type", self.core.bp_type,
               {"one_bit", "none"})
        # [stack] layout sanity up front — a bad layout must not surface
        # as a VMError from the run SUMMARY after an hours-long
        # simulation already completed (engine/vm.VMManager asserts the
        # same invariants at reporting time).
        from graphite_tpu.engine.vm import START_DATA, START_DYNAMIC
        end_stack = self.stack_base \
            + self.num_tiles * self.stack_size_per_core
        if not (START_DATA < self.stack_base < end_stack < START_DYNAMIC):
            raise ConfigError(
                f"[stack] layout invalid: stacks "
                f"{self.stack_base:#x}-{end_stack:#x} must sit between "
                f"the data segment ({START_DATA:#x}) and the dynamic "
                f"segment ({START_DYNAMIC:#x})")
        # Streaming segmented ingest composes only with the validated
        # subset; every other combination refuses up front (the round-15
        # resident rule: a config that would quietly run a DIFFERENT
        # program is worse than one that refuses).
        if self.segment_events > 0:
            if self.shard_state != "replicated":
                raise ConfigError(
                    "trace/segment_events (streaming ingest) requires "
                    "tpu/shard_state=replicated — the resident tile-"
                    "sharded program does not compose with segment "
                    "swaps yet (tile_shards > 1 replicated is fine)")
            if self.fast_forward > 0:
                raise ConfigError(
                    "trace/segment_events with tpu/fast_forward > 0 is "
                    "not validated: analytic spans widen the trace "
                    "lookahead past the segment overrun guard — run "
                    "streamed traces with fast_forward=0")
            L = self.ingest_lookahead
            if self.segment_events < 2 * L:
                raise ConfigError(
                    f"trace/segment_events={self.segment_events} must "
                    f"be >= 2x the engine read lookahead ({L} events — "
                    f"the window cache's resident span); smaller "
                    f"segments cannot guarantee swap progress")

    def module_freq_ghz(self, module: DVFSModule) -> float:
        """Initial frequency of a module from its DVFS domain."""
        for freq, modules in self.dvfs_domains:
            if int(module) in modules:
                return freq
        return self.max_frequency_ghz

    @classmethod
    def from_config(cls, cfg: Config, num_tiles: Optional[int] = None) -> "SimParams":
        T = num_tiles if num_tiles is not None else cfg.get_int("general/total_cores")
        mesh_w = int(math.floor(math.sqrt(T)))
        mesh_h = int(math.ceil(T / mesh_w))

        tiles = parse_tile_model_list(cfg.get_str("tile/model_list"))
        # Sequential tuple fill, exactly the reference's semantics
        # (config.cc:365-460): each tuple covers ``count`` tiles in
        # order, "default" count = all T, counts must sum to exactly T.
        # Core types MAY mix (heterogeneous simple/iocoom per tile —
        # the engine gates iocoom semantics on a per-tile mask); cache
        # configs must agree across tuples and are rejected loudly
        # otherwise — per-tile cache GEOMETRY mixes would break the
        # packed [T, sets, ways] state layout, and silently running the
        # first tuple mis-simulated the config (VERDICT r2 weak #5).
        per_tile_core: list = []
        cache_names = set()
        for cnt_s, ctype, n1i, n1d, n2 in tiles:
            try:
                cnt = T if cnt_s == "default" else int(cnt_s)
            except ValueError:
                raise ConfigError(
                    f"bad tile count {cnt_s!r} in [tile]/model_list "
                    "(a number or 'default')") from None
            if cnt < 1:
                # A dropped tuple would silently mis-simulate the config
                # (VERDICT r2 weak #5) — reject instead.
                raise ConfigError(
                    f"tile count {cnt} in [tile]/model_list must be >= 1")
            ctype = "simple" if ctype == "default" else ctype
            if ctype not in ("simple", "iocoom"):
                raise ConfigError(
                    f"unknown core type {ctype!r} in [tile]/model_list "
                    "(valid: simple, iocoom)")
            if len(per_tile_core) + cnt > T:
                raise ConfigError(
                    f"[tile]/model_list covers more than total_cores={T} "
                    "tiles")
            per_tile_core.extend([ctype] * cnt)
            # Normalize before comparing: 'default' IS T1 (reference
            # config.cc DEFAULT_CACHE_TYPE), so mixing the two spellings
            # is homogeneous.
            cache_names.add(tuple("T1" if n == "default" else n
                                  for n in (n1i, n1d, n2)))
        if len(per_tile_core) != T:
            raise ConfigError(
                f"[tile]/model_list covers {len(per_tile_core)} of "
                f"total_cores={T} tiles")
        if len(cache_names) > 1:
            raise ConfigError(
                "heterogeneous cache configs in [tile]/model_list are "
                f"not implemented (got {sorted(cache_names)}); per-tile "
                "cache geometry mixes would break the packed state "
                "layout — core-type mixes are supported")
        l1i_name, l1d_name, l2_name = next(iter(cache_names))
        if any(c == "iocoom" for c in per_tile_core):
            core_type = "iocoom"
            iocoom_mask = tuple(c == "iocoom" for c in per_tile_core) \
                if any(c == "simple" for c in per_tile_core) else None
        else:
            core_type = "simple"
            iocoom_mask = None
        l1i_name = "T1" if l1i_name == "default" else l1i_name
        l1d_name = "T1" if l1d_name == "default" else l1d_name
        l2_name = "T1" if l2_name == "default" else l2_name

        l1i = CacheParams.from_config(cfg, f"l1_icache/{l1i_name}", "l1_icache")
        l1d = CacheParams.from_config(cfg, f"l1_dcache/{l1d_name}", "l1_dcache")
        l2 = CacheParams.from_config(cfg, f"l2_cache/{l2_name}", "l2_cache")

        dram = DramParams.from_config(cfg, T)
        protocol = cfg.get_str("caching_protocol/type")
        if protocol.startswith("pr_l1_sh_l2"):
            # Shared-distributed L2: the "directory" is the per-tile L2
            # slice itself (tags + state + L1-sharer tracking), so its
            # geometry and access latency come from the L2 cache config
            # and the sharer-tracking knobs from [l2_directory]
            # (reference: l2_directory_cfg.cc, l2_cache_cntlr.cc).
            directory = DirectoryParams(
                total_entries=l2.num_sets * l2.associativity,
                associativity=l2.associativity,
                max_hw_sharers=cfg.get_int("l2_directory/max_hw_sharers"),
                directory_type=cfg.get_str("l2_directory/directory_type"),
                access_cycles=l2.access_cycles,
                limitless_trap_cycles=cfg.get_int(
                    "limitless/software_trap_penalty"),
                inv_ack_cycles=_positive(
                    cfg.get_int("dram_directory/inv_ack_combining_cycles", 1),
                    "dram_directory/inv_ack_combining_cycles"),
            )
        else:
            directory = DirectoryParams.from_config(
                cfg, T, l2, num_slices=dram.num_controllers)

        scheme = cfg.get_str("clock_skew_management/scheme")
        if scheme == "lax_p2p":
            scheme = "lax_barrier"  # subsumed on TPU (see SURVEY.md section 5.7)
        quantum_ns = cfg.get_int("clock_skew_management/lax_barrier/quantum")

        return cls(
            num_tiles=T,
            mesh_width=mesh_w,
            mesh_height=mesh_h,
            max_frequency_ghz=cfg.get_float("general/max_frequency"),
            quantum_ps=int(ns_to_ps(quantum_ns)),
            clock_skew_scheme=scheme,
            max_threads_per_core=_positive(
                cfg.get_int("general/max_threads_per_core", 1),
                "general/max_threads_per_core"),
            thread_switch_quantum_ps=int(ns_to_ps(_positive(
                cfg.get_int("thread_scheduling/switch_quantum", 10_000),
                "thread_scheduling/switch_quantum"))),
            core=CoreParams.from_config(cfg, core_type, iocoom_mask),
            l1i=l1i,
            l1d=l1d,
            l2=l2,
            protocol=protocol,
            l2_directory_type=cfg.get_str("l2_directory/directory_type"),
            l2_max_hw_sharers=cfg.get_int("l2_directory/max_hw_sharers"),
            directory=directory,
            dram=dram,
            net_user=NetworkParams.from_config(
                cfg, "user", num_tiles=T,
                net_freq_ghz=cfg.get_float("general/max_frequency")),
            net_memory=NetworkParams.from_config(
                cfg, "memory", num_tiles=T,
                net_freq_ghz=cfg.get_float("general/max_frequency")),
            dvfs_domains=parse_dvfs_domains(cfg.get_str("dvfs/domains")),
            dvfs_sync_delay_cycles=cfg.get_int("dvfs/synchronization_delay"),
            syscall_cost_cycles=_syscall_costs(cfg),
            stack_base=cfg.get_int("stack/stack_base"),
            stack_size_per_core=_positive(
                cfg.get_int("stack/stack_size_per_core"),
                "stack/stack_size_per_core"),
            track_miss_types=(l1d.track_miss_types or l2.track_miss_types),
            enable_core_modeling=cfg.get_bool("general/enable_core_modeling"),
            enable_power_modeling=cfg.get_bool("general/enable_power_modeling"),
            technology_node=cfg.get_int("general/technology_node"),
            models_enabled_at_start=(
                cfg.get_bool("general/enable_core_modeling")
                and not cfg.get_bool(
                    "general/trigger_models_within_application")),
            stats_enabled=cfg.get_bool("statistics_trace/enabled"),
            progress_enabled=cfg.get_bool("progress_trace/enabled"),
            stat_interval_ps=int(ns_to_ps(min(
                (cfg.get_int("statistics_trace/sampling_interval")
                 if cfg.get_bool("statistics_trace/enabled") else 1 << 40),
                (cfg.get_int("progress_trace/interval")
                 if cfg.get_bool("progress_trace/enabled") else 1 << 40),
                (cfg.get_int("runtime_energy_modeling/interval", 1000)
                 if cfg.get_bool(
                     "runtime_energy_modeling/power_trace/enabled", False)
                 else 1 << 40),
                _telemetry_interval_ns(cfg)))),
            power_trace_enabled=cfg.get_bool(
                "runtime_energy_modeling/power_trace/enabled", False),
            telemetry_enabled=cfg.get_bool("telemetry/enabled", False),
            max_stat_samples=cfg.get_int("tpu/max_stat_samples", 1024),
            block_events=_block_events(cfg.get_int("tpu/block_events", 16)),
            window_cache=cfg.get_bool("tpu/window_cache", True),
            max_events_per_quantum=cfg.get_int("tpu/max_events_per_quantum"),
            directory_conflict_rounds=cfg.get_int("tpu/directory_conflict_rounds"),
            rounds_per_quantum=cfg.get_int("tpu/rounds_per_quantum", 4),
            quanta_per_step=cfg.get_int("tpu/quanta_per_step"),
            max_inv_fanout_per_round=_positive(cfg.get_int(
                "tpu/max_inv_fanout_per_round", 8),
                "tpu/max_inv_fanout_per_round"),
            miss_chain=_miss_chain(cfg.get_int("tpu/miss_chain", 0)),
            max_resolve_rounds=_positive(
                cfg.get_int("tpu/max_resolve_rounds", 4),
                "tpu/max_resolve_rounds"),
            fanout_replay=cfg.get_bool("tpu/fanout_replay", True),
            pallas_kernels=_pallas_kernels(
                cfg.get_str("tpu/pallas_kernels", "auto")),
            tile_shards=_tile_shards(
                cfg.get_str("tpu/tile_shards", "1"), T),
            shard_state=_shard_state(
                cfg.get_str("tpu/shard_state", "replicated")),
            route_capacity=_nonneg(
                cfg.get_int("tpu/route_capacity", 0),
                "tpu/route_capacity"),
            channel_depth=cfg.get_int("tpu/channel_depth", 16),
            cond_replay=cfg.get_bool("tpu/cond_replay", False),
            fast_forward=_fast_forward(
                cfg.get_int("tpu/fast_forward", 0)),
            fast_forward_span_ps=int(ns_to_ps(_nonneg(
                cfg.get_int("tpu/fast_forward_span", 0),
                "tpu/fast_forward_span"))),
            segment_events=_nonneg(
                cfg.get_int("trace/segment_events", 0),
                "trace/segment_events"),
        )
