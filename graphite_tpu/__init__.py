"""graphite_tpu — a TPU-native distributed many-core timing simulator.

A ground-up JAX/XLA re-design of the capabilities of MIT's Graphite
(reference: /root/reference, HPCA 2010): it consumes per-tile
instruction/memory event streams and advances thousands of simulated
tiles — core pipeline models, private/shared L1/L2 cache hierarchies with
directory coherence, electrical-mesh/optical NoC models with contention
queueing, DRAM, DVFS, and power accounting — as vmapped per-tile state
machines stepped one lax-barrier quantum at a time.  The tile axis is
sharded over a `jax.sharding.Mesh` so ICI collectives replace the
reference's socket transport (reference: common/transport/socktransport.cc)
and MCP control plane (reference: common/system/mcp.cc).

Execution model (contrast with the reference):
  * Graphite runs one host thread per simulated tile, each advancing its
    tile event-by-event, with TCP sockets carrying modeled packets between
    host processes and a barrier server bounding clock skew
    (reference: common/system/clock_skew_management_schemes/).
  * graphite_tpu runs *all* tiles as one array program: simulation state is
    a pytree of arrays shaped [num_tiles, ...]; each jitted step advances
    every tile through one synchronization quantum; the lax-barrier is a
    reduction over the tile axis instead of a server thread.

Simulated time is int64 picoseconds throughout, matching the reference's
Time convention (reference: common/misc/time_types.h:7-60), so the package
enables jax_enable_x64 at import.
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from graphite_tpu.config import Config, ConfigError, load_config  # noqa: E402,F401
