"""graphite_tpu — a TPU-native distributed many-core timing simulator.

A ground-up JAX/XLA re-design of the capabilities of MIT's Graphite
(reference: /root/reference, HPCA 2010): it consumes per-tile
instruction/memory event streams and advances thousands of simulated
tiles — core pipeline models, private/shared L1/L2 cache hierarchies with
directory coherence, electrical-mesh/optical NoC models with contention
queueing, DRAM, DVFS, and power accounting — as vmapped per-tile state
machines stepped one lax-barrier quantum at a time.  The tile axis is
sharded over a `jax.sharding.Mesh` so ICI collectives replace the
reference's socket transport (reference: common/transport/socktransport.cc)
and MCP control plane (reference: common/system/mcp.cc).

Execution model (contrast with the reference):
  * Graphite runs one host thread per simulated tile, each advancing its
    tile event-by-event, with TCP sockets carrying modeled packets between
    host processes and a barrier server bounding clock skew
    (reference: common/system/clock_skew_management_schemes/).
  * graphite_tpu runs *all* tiles as one array program: simulation state is
    a pytree of arrays shaped [num_tiles, ...]; each jitted step advances
    every tile through one synchronization quantum; the lax-barrier is a
    reduction over the tile axis instead of a server thread.

Simulated time is int64 picoseconds throughout, matching the reference's
Time convention (reference: common/misc/time_types.h:7-60), so the package
enables jax_enable_x64 at import.
"""

import os as _os

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the fused quantum step is a large XLA
# program (tens of seconds per unique (params, shapes) key); caching makes
# repeated bench/test/CLI invocations compile-free.  Honors an explicit
# JAX_COMPILATION_CACHE_DIR; otherwise uses <repo>/.jax_cache — only when
# the package actually sits in a repo checkout (pyproject.toml beside it),
# so a site-packages install does not grow a cache inside the environment.
if not _os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    _root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    if _os.path.exists(_os.path.join(_root, "pyproject.toml")):
        _jax.config.update("jax_compilation_cache_dir",
                           _os.path.join(_root, ".jax_cache"))
_jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
_jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

__version__ = "0.1.0"

from graphite_tpu.config import Config, ConfigError, load_config  # noqa: E402,F401
