"""Command-line launcher.

Plays the role of the reference's Makefile/tools launcher layer
(reference: tools/, tests/Makefile.tests:44-78): compose a config from a
file plus ``--section/key=value`` overrides and run a simulation.

Usage:
    graphite-tpu run [-c CONFIG] [--section/key=value ...] --trace TRACE.npz
    graphite-tpu sweep [-c CONFIG] --trace TRACE.npz --sweep key=v1,v2 ...
    graphite-tpu params [-c CONFIG] [--section/key=value ...]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import List, Optional

from graphite_tpu.config import load_config, parse_overrides
from graphite_tpu.params import SimParams


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="graphite-tpu")
    sub = ap.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="run a simulation from a trace")
    run.add_argument("-c", "--config", default=None)
    run.add_argument("--trace", required=True, help="trace .npz path")
    run.add_argument("-o", "--output", default=None, help="summary output path")
    run.add_argument("--telemetry-dir", default=None, metavar="DIR",
                     help="enable run telemetry (host span tracing + "
                          "[telemetry] round metrics) and write "
                          "run_report.json + run_trace.json under DIR")
    run.add_argument("--segment-events", type=int, default=None,
                     metavar="N",
                     help="streaming segmented ingest (round 16): keep "
                          "only two N-event device-resident trace "
                          "segments (active + prefetch) and stream the "
                          "host trace through them — traces bigger than "
                          "HBM run whole, bit-identically. Shorthand "
                          "for --trace/segment_events=N. Unvalidated "
                          "combinations (resident shard_state, "
                          "fast_forward, multi-thread scheduling) are "
                          "rejected loudly")

    sw = sub.add_parser(
        "sweep", help="run V config variants of one trace as a single "
                      "vmapped device program")
    sw.add_argument("-c", "--config", default=None)
    sw.add_argument("--trace", required=True, help="trace .npz path")
    sw.add_argument("--sweep", action="append", default=[], metavar="SPEC",
                    help="sweep axis: section/key=v1,v2,... — repeat for "
                         "a cross product; join keys with ';' inside one "
                         "flag to zip them (sweep/space.py grammar). "
                         "Keys must be VARIANT leaves (timing numerics); "
                         "structural keys are rejected. Required unless "
                         "--resume replays an existing journal.")
    sw.add_argument("-o", "--output", default=None,
                    help="write per-variant JSON rows here (shaped like a "
                         "bench result: {'detail': {label: row}}, so "
                         "tools/results_db.py add ingests it directly)")
    sw.add_argument("--serve", action="store_true",
                    help="run through the fault-tolerant SweepService "
                         "(crash-safe ticket journal, bucket bisection, "
                         "preempt/resume — sweep/service.py) instead of "
                         "the bare driver; requires --journal")
    sw.add_argument("--resume", action="store_true",
                    help="recover an interrupted service run from "
                         "--journal (re-queues in-flight tickets, "
                         "resumes preempted buckets, never re-runs DONE "
                         "ones); implies --serve, --sweep optional")
    sw.add_argument("--journal", default=None, metavar="DIR",
                    help="service journal directory (ticket records + "
                         "preemption checkpoints)")
    sw.add_argument("--db", default=None, metavar="PATH",
                    help="results_db sqlite path: completed tickets are "
                         "stored and identical re-submissions are served "
                         "from cache without simulating")
    sw.add_argument("--segment-events", type=int, default=None,
                    metavar="N",
                    help="key tickets on the N-event streamed content "
                         "hash (events/segments.py) instead of the "
                         "whole-trace hash — identical streamed "
                         "submissions share DONE tickets and cached "
                         "rows. Shorthand for --trace/segment_events=N "
                         "(buckets still execute whole-trace)")
    sw.add_argument("--metrics-path", default=None, metavar="PATH",
                    help="(--serve only) enable the obs metrics "
                         "registry and write its Prometheus text "
                         "exposition here, atomically after every "
                         "drain and once more on exit (ticket_latency_s"
                         " / first_result_latency_s histograms, "
                         "cache_hit_ratio, tickets_in_state, ...)")

    st = sub.add_parser(
        "status", help="summarize a sweep-service journal: per-state "
                       "counts and a per-ticket table (works on a live "
                       "service's journal — records are atomic)")
    st.add_argument("-c", "--config", default=None)
    st.add_argument("--journal", required=True, metavar="DIR",
                    help="service journal directory to fold")
    st.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the raw journal_status dict as JSON "
                         "instead of the table")

    par = sub.add_parser("params", help="print derived simulation parameters")
    par.add_argument("-c", "--config", default=None)
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from graphite_tpu.compile_cache import enable_compile_cache
    enable_compile_cache()
    overrides, rest = parse_overrides(argv)
    args = _build_parser().parse_args(rest)
    telemetry_dir = getattr(args, "telemetry_dir", None)
    from graphite_tpu import obs
    if telemetry_dir:
        obs.enable_tracing()
    with obs.span("config.load", path=args.config or "<defaults>"):
        cfg = load_config(args.config, overrides=overrides)
    if telemetry_dir and not any(p == "telemetry/enabled"
                                 for p, _ in overrides):
        cfg.set("telemetry/enabled", "true")
    if getattr(args, "segment_events", None) is not None:
        cfg.set("trace/segment_events", int(args.segment_events))
    from graphite_tpu import log as logmod
    logmod.configure(cfg)

    if args.command == "params":
        params = SimParams.from_config(cfg)
        print(json.dumps(dataclasses.asdict(params), indent=2, default=str))
        return 0

    if args.command == "run":
        try:
            return _run_command(cfg, args, telemetry_dir)
        finally:
            if telemetry_dir:
                # The tracer is process-global; a long-lived embedder
                # (tests, notebooks) must not keep accumulating spans
                # after this run's artifacts are written.
                obs.enable_tracing(False)

    if args.command == "sweep":
        return _sweep_command(cfg, args)

    if args.command == "status":
        return _status_command(args)

    return 2


def _sweep_command(cfg, args) -> int:
    import time

    from graphite_tpu.events.schema import Trace
    from graphite_tpu.sweep import SweepDriver, build_variants
    from graphite_tpu.time_base import ps_to_ns

    if args.serve or args.resume:
        return _serve_command(cfg, args)
    if not args.sweep:
        print("sweep: --sweep is required (unless --serve/--resume)",
              file=sys.stderr)
        return 2
    trace = Trace.load(args.trace)
    variants = build_variants(cfg, args.sweep, num_tiles=trace.num_tiles)
    drv = SweepDriver(trace)
    tickets = [(label, overrides, drv.submit(p))
               for label, overrides, p in variants]
    t0 = time.perf_counter()
    results = drv.drain()
    host_s = time.perf_counter() - t0
    detail = {}
    for label, overrides, ticket in tickets:
        s = results[ticket]
        d = s.to_dict()
        detail[label] = {
            "kind": "sweep_variant",
            "overrides": overrides,
            "num_tiles": d["num_tiles"],
            "completion_time_ns": d["completion_time_ns"],
            "total_instructions": d["total_instructions"],
            "all_done": d["all_done"],
            "quanta": d["quanta"],
            "aggregate": d["aggregate"],
        }
        # Round-12 adaptive-fidelity attribution rides the variant rows
        # when tpu/fast_forward > 0, so `results_db.py add` chains the
        # ff-quanta-fraction regression flag over sweep output too.
        for k in ("ff_rounds", "ff_quanta", "ff_events",
                  "ff_quanta_frac"):
            if k in d:
                detail[label][k] = d[k]
        print(f"{label}: completion "
              f"{ps_to_ns(s.completion_time_ps):.1f} ns, "
              f"{'done' if d['all_done'] else 'INCOMPLETE'}, "
              f"{d['total_instructions']} instrs")
    out = {
        "metric": "sweep",
        "workload": args.trace,
        "variants": len(tickets),
        "host_seconds": round(host_s, 3),
        "variants_per_sec": round(len(tickets) / max(host_s, 1e-9), 3),
        "compiles": drv.compiles_observed,
        "detail": detail,
    }
    line = json.dumps(out)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line + "\n")
    print(line)
    return 0


def _serve_command(cfg, args) -> int:
    """sweep --serve / --resume: the fault-tolerant service path.  New
    --sweep points are submitted as tickets; with --resume the journal's
    recovered tickets (re-queued in-flight work, preempted buckets) are
    served too.  Output rows mirror the driver path's shape so
    results_db ingestion and the recovery gate's bit-identity diff work
    unchanged."""
    import time

    from graphite_tpu.events.schema import Trace
    from graphite_tpu.sweep import SweepService, parse_sweep_spec

    journal = args.journal or cfg.get_str("service/journal_dir", "")
    if not journal:
        print("sweep --serve/--resume needs --journal DIR",
              file=sys.stderr)
        return 2
    if not args.sweep and not args.resume:
        print("sweep --serve: nothing to do (no --sweep and no "
              "--resume)", file=sys.stderr)
        return 2
    trace = Trace.load(args.trace)
    svc = SweepService(trace, journal, cfg=cfg, db_path=args.db,
                       metrics_path=args.metrics_path)
    for overrides in parse_sweep_spec(args.sweep) if args.sweep else []:
        svc.submit(overrides)
    t0 = time.perf_counter()
    try:
        tickets = svc.serve()
    finally:
        # Exposition on exit even when serve() raises: the scrape file
        # reflects whatever the process actually got through.
        svc.write_metrics()
    host_s = time.perf_counter() - t0
    detail = {}
    for t in sorted(tickets.values(), key=lambda t: t.ticket):
        if t.status == "done":
            row = dict(t.summary)
            row["overrides"] = t.overrides
            row["ticket"] = t.ticket
            row["status"] = t.status
            row["from_cache"] = t.from_cache
        else:
            row = {"kind": "service_ticket", "ticket": t.ticket,
                   "overrides": t.overrides, "status": t.status,
                   "error": t.error}
        detail[t.label] = row
        print(f"ticket {t.ticket} [{t.label}]: {t.status}"
              f"{' (cache)' if t.from_cache else ''}"
              f"{' — ' + t.error if t.error else ''}")
    served = sum(1 for t in tickets.values() if t.status == "done")
    lat = svc.latency_stats()
    out = {
        "metric": "sweep_service",
        "workload": args.trace,
        "tickets": len(tickets),
        "variants": served,
        "host_seconds": round(host_s, 3),
        "variants_per_sec": round(served / max(host_s, 1e-9), 3),
        "p50_first_result_s": lat["p50_first_result_s"],
        "p99_first_result_s": lat["p99_first_result_s"],
        "cache_hit_ratio": lat["cache_hit_ratio"],
        "compiles": svc.compiles_observed,
        "stats": svc.stats,
        "detail": detail,
    }
    if args.metrics_path:
        out["metrics_path"] = args.metrics_path
    line = json.dumps(out)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line + "\n")
    print(line)
    quarantined = sum(1 for t in tickets.values()
                      if t.status in ("quarantined", "failed"))
    return 0 if quarantined == 0 else 3


def _status_command(args) -> int:
    """status --journal DIR: fold the journal into a per-state /
    per-ticket table without loading a trace or building params."""
    import os

    from graphite_tpu.sweep.service import STATES, journal_status

    if not os.path.isdir(args.journal):
        print(f"status: no journal directory at {args.journal!r}",
              file=sys.stderr)
        return 2
    st = journal_status(args.journal)
    if args.as_json:
        print(json.dumps(st))
        return 0
    counts = " ".join(f"{s}={st['counts'][s]}" for s in STATES)
    print(f"journal {st['journal_dir']}: {len(st['tickets'])} tickets "
          f"({counts})")
    for k in ("p50_first_result_s", "p99_first_result_s",
              "p50_ticket_latency_s", "p99_ticket_latency_s"):
        if st[k] is not None:
            print(f"  {k} = {st[k]:.3f}")
    for r in st["tickets"]:
        tm = r["times"]
        when = ""
        if "submit" in tm and "done" in tm:
            when = f"  ({tm['done'] - tm['submit']:.3f}s)"
        elif "submit" in tm and "first_result" in tm:
            when = (f"  (first result after "
                    f"{tm['first_result'] - tm['submit']:.3f}s)")
        cache = " (cache)" if r["from_cache"] else ""
        err = f" — {r['error']}" if r["error"] else ""
        print(f"  ticket {r['ticket']:4d} [{r['label']}]: "
              f"{r['status']}{cache}{when}{err}")
    return 0


def _run_command(cfg, args, telemetry_dir: Optional[str]) -> int:
    from graphite_tpu import obs
    from graphite_tpu.engine.sim import run_simulation_from_trace

    summary = run_simulation_from_trace(cfg, args.trace)
    text = summary.render()
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        print(text)
    # [runtime_energy_modeling/power_trace] enabled=true: write the
    # per-interval power file beside the summary (reference
    # carbon_sim.cfg:141-145).
    if cfg.get_bool("runtime_energy_modeling/power_trace/enabled",
                    False):
        ptpath = (args.output or "sim") + ".power.csv"
        summary.write_power_trace(ptpath)
    if telemetry_dir:
        paths = summary.write_telemetry(
            telemetry_dir, tracer=obs.get_tracer(),
            workload=args.trace)
        print(f"telemetry: {paths['report']} "
              f"{paths['trace']} (open the trace in "
              f"https://ui.perfetto.dev or chrome://tracing)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
