"""Command-line launcher.

Plays the role of the reference's Makefile/tools launcher layer
(reference: tools/, tests/Makefile.tests:44-78): compose a config from a
file plus ``--section/key=value`` overrides and run a simulation.

Usage:
    graphite-tpu run [-c CONFIG] [--section/key=value ...] --trace TRACE.npz
    graphite-tpu params [-c CONFIG] [--section/key=value ...]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import List, Optional

from graphite_tpu.config import load_config, parse_overrides
from graphite_tpu.params import SimParams


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="graphite-tpu")
    sub = ap.add_subparsers(dest="command", required=True)

    run = sub.add_parser("run", help="run a simulation from a trace")
    run.add_argument("-c", "--config", default=None)
    run.add_argument("--trace", required=True, help="trace .npz path")
    run.add_argument("-o", "--output", default=None, help="summary output path")
    run.add_argument("--telemetry-dir", default=None, metavar="DIR",
                     help="enable run telemetry (host span tracing + "
                          "[telemetry] round metrics) and write "
                          "run_report.json + run_trace.json under DIR")

    par = sub.add_parser("params", help="print derived simulation parameters")
    par.add_argument("-c", "--config", default=None)
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    overrides, rest = parse_overrides(argv)
    args = _build_parser().parse_args(rest)
    telemetry_dir = getattr(args, "telemetry_dir", None)
    from graphite_tpu import obs
    if telemetry_dir:
        obs.enable_tracing()
    with obs.span("config.load", path=args.config or "<defaults>"):
        cfg = load_config(args.config, overrides=overrides)
    if telemetry_dir and not any(p == "telemetry/enabled"
                                 for p, _ in overrides):
        cfg.set("telemetry/enabled", "true")
    from graphite_tpu import log as logmod
    logmod.configure(cfg)

    if args.command == "params":
        params = SimParams.from_config(cfg)
        print(json.dumps(dataclasses.asdict(params), indent=2, default=str))
        return 0

    if args.command == "run":
        try:
            return _run_command(cfg, args, telemetry_dir)
        finally:
            if telemetry_dir:
                # The tracer is process-global; a long-lived embedder
                # (tests, notebooks) must not keep accumulating spans
                # after this run's artifacts are written.
                obs.enable_tracing(False)

    return 2


def _run_command(cfg, args, telemetry_dir: Optional[str]) -> int:
    from graphite_tpu import obs
    from graphite_tpu.engine.sim import run_simulation_from_trace

    summary = run_simulation_from_trace(cfg, args.trace)
    text = summary.render()
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        print(text)
    # [runtime_energy_modeling/power_trace] enabled=true: write the
    # per-interval power file beside the summary (reference
    # carbon_sim.cfg:141-145).
    if cfg.get_bool("runtime_energy_modeling/power_trace/enabled",
                    False):
        ptpath = (args.output or "sim") + ".power.csv"
        summary.write_power_trace(ptpath)
    if telemetry_dir:
        paths = summary.write_telemetry(
            telemetry_dir, tracer=obs.get_tracer(),
            workload=args.trace)
        print(f"telemetry: {paths['report']} "
              f"{paths['trace']} (open the trace in "
              f"https://ui.perfetto.dev or chrome://tracing)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
