"""Device-mesh distribution of the simulation (tile-axis sharding)."""

from graphite_tpu.parallel.mesh import (  # noqa: F401
    make_mesh, shard_pytree, tile_sharding)
