"""Tile-axis sharding over a jax device mesh.

This is the TPU-native replacement for the reference's multi-process
distribution: Graphite partitions target tiles across host processes with
TCP sockets carrying modeled packets between them and a process barrier in
the transport (reference: common/misc/config.h:173
computeProcessToTileMapping, common/transport/socktransport.cc:61-287).
Here the tile axis of every state array is sharded over a
``jax.sharding.Mesh``; cross-tile gathers/scatters in the resolve phase
(requests to home directories, invalidation fan-out) compile to XLA
collectives riding ICI, and the quantum min-reduction is the barrier.

Multi-host scaling rides the same mechanism: `jax.distributed` extends the
mesh across hosts (ICI within a slice, DCN across), with no engine changes
— the reference needed ssh spawners and a socket fabric for the same reach
(tools/spawn_master.py).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TILE_AXIS = "tiles"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              axis: str = TILE_AXIS) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (axis,))


def tile_sharding(mesh: Mesh, num_tiles: int):
    """Sharding-spec pytree builder: arrays with a leading tile axis are
    split over the mesh; global arrays (sync objects, the quantum boundary)
    are replicated."""

    def spec_for(leaf: Any):
        shape = np.shape(leaf)
        if len(shape) >= 1 and shape[0] == num_tiles:
            return NamedSharding(mesh, P(TILE_AXIS))
        return NamedSharding(mesh, P())

    return spec_for


def shard_pytree(tree: Any, mesh: Mesh, num_tiles: int) -> Any:
    """Place a pytree (SimState / TraceArrays) onto the mesh, tile-sharded."""
    spec = tile_sharding(mesh, num_tiles)
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(leaf, spec(leaf)), tree)
