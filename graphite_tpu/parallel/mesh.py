"""Tile-axis sharding over a jax device mesh.

This is the TPU-native replacement for the reference's multi-process
distribution: Graphite partitions target tiles across host processes with
TCP sockets carrying modeled packets between them and a process barrier in
the transport (reference: common/misc/config.h:173
computeProcessToTileMapping, common/transport/socktransport.cc:61-287).
Here the tile axis of every state array is sharded over a
``jax.sharding.Mesh``; cross-tile gathers/scatters in the resolve phase
(requests to home directories, invalidation fan-out) compile to XLA
collectives riding ICI, and the quantum min-reduction is the barrier.

Multi-host scaling rides the same mechanism: `jax.distributed` extends the
mesh across hosts (ICI within a slice, DCN across), with no engine changes
— the reference needed ssh spawners and a socket fabric for the same reach
(tools/spawn_master.py).  tools/multihost_dryrun.py (tests/test_multihost.py)
exercises the two-process path: coordinator-connected processes run one
fused megastep over a global 8-device mesh with collectives crossing the
process boundary (capability-probed first — the CPU backend refuses
cross-process computations).

Two sharding mechanisms live here, one current and one superseded:

  * **Explicit shard_map** (``tpu/tile_shards`` > 1, round 11 — the
    CURRENT path): :func:`shard_wrap` wraps the quantum program in
    ``shard_map`` over this mesh with every operand replicated; inside,
    the engine slices ONLY the block window's operands to the shard's
    T/S tiles (engine/kernels/window.run_window_sharded), all_gathers
    the walk's outputs back, and reduces the quantum barrier with an
    explicit ``pmin`` — the ZSim bound-weave shape: a shard-local bound
    phase with ZERO cross-device traffic, then a bounded set of
    explicit collectives.
  * **GSPMD auto-sharding** (:func:`shard_pytree` under a whole-program
    jit — SUPERSEDED as the scale-out path): device_put the state
    tile-sharded and let the partitioner guess.  Measured 0.95x on 8
    CPU devices (pure overhead: resolve's full-T gathers/scatters force
    resharding of everything — PROFILE.md round 11).  It remains the
    placement layer for multi-host dryruns and the resharding-on-restore
    tests, not the performance path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TILE_AXIS = "tiles"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              axis: str = TILE_AXIS) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (axis,))


def shard_wrap(tile_shards: int, fn: Callable, nargs: int) -> Callable:
    """Wrap ``fn(*nargs arrays/pytrees)`` in ``shard_map`` over the first
    ``tile_shards`` devices when sharding is on; the identity at 1 — the
    single-device program is untouched, bit for bit.

    Every in/out spec is REPLICATED (``P()``): the engine's state stays
    whole on every device, and the sharded work happens INSIDE ``fn``
    via ``lax.axis_index`` slicing (the window walk) + explicit
    collectives (all_gather, the pmin barrier).  Replication also makes
    the bit-identity contract structural — each shard computes the same
    full-T arrays wherever it is not explicitly sliced.
    ``check_rep=False`` because the engine's while_loops and explicit
    collectives defeat the replication checker, not because anything is
    unreplicated."""
    if tile_shards <= 1:
        return fn
    from jax.experimental.shard_map import shard_map
    devices = jax.devices()
    if len(devices) < tile_shards:
        raise ValueError(
            f"tpu/tile_shards={tile_shards} needs at least that many "
            f"devices; jax sees {len(devices)} (force virtual CPU "
            f"devices with --xla_force_host_platform_device_count)")
    mesh = make_mesh(devices[:tile_shards])
    return shard_map(fn, mesh=mesh, in_specs=(P(),) * nargs,
                     out_specs=P(), check_rep=False)


# Tile-axis position per engine array field.  Engine arrays keep small
# structural dims (assoc ways, bitmap words, channel slots, event fields)
# LEADING so the minor two dims stay large — TPU pads the minor dims to
# (8, 128) tiles, and a trailing assoc-sized axis wastes 8-16x memory and
# bandwidth — which puts the tile axis at position 0, 1, or 2 depending on
# the array.  Matching by field name (not by axis size) avoids sharding a
# structural axis that happens to equal the tile count (e.g. channel_depth
# == num_tiles).
_TILE_AXIS_BY_FIELD = {
    "word": 1, "meta": 1,            # CacheArrays [A, T, sets] / trace
    "win_meta": 1,                   # [3, T, WC] window-cache slice
    #   (WC = 4K since the round-9 boundary-spanning windows; win_addr/
    #   win_base/win_seat and the round-9 chain_fanout_served/
    #   chain_fallback counters are tile-leading, covered by the
    #   default axis-0 rule below)
    "dir_word": 1,                   # [A, T*dsets] (tile-major flat)
    "dir_sharers": 1,                # [W*A, T*dsets]
    "ch_time": 1,                    # [D, T, T]
    "mq_req": 1,                     # [P, T] banked miss chains
    "mq_delta": 1, "mq_extra": 1,    # (blocking chain replay, round 7)
    "lq_ready": 1, "sq_ready": 1,    # [entries, T]
    "dram_ring_start": 1, "dram_ring_end": 1,   # [R, T]
    "link_free_mem": 1,              # [NUM_DIRS, T]
    "stat_icount": 1,                # [S, T] progress-trace snapshots
    "tel_cursor": 1,                 # [S, T] telemetry cursor snapshots
    "tel_pend": 1,                   # [S, T] telemetry pend_kind snapshots
}

# Fields whose tile axis is FLATTENED with a per-tile structural axis
# (directory sets): tile-major, so an even split over the flat axis is an
# even split over tiles.
_TILE_MAJOR_FLAT = {"dir_word", "dir_sharers"}


def tile_sharding(mesh: Mesh, num_tiles: int):
    """Sharding-spec builder: each array's tile axis is split over the
    mesh; global arrays (sync objects, the quantum boundary) replicate."""

    def spec_for(name: str, leaf: Any):
        shape = np.shape(leaf)
        ax = _TILE_AXIS_BY_FIELD.get(name, 0)
        ok = len(shape) > ax and (
            shape[ax] == num_tiles
            or (name in _TILE_MAJOR_FLAT and shape[ax] % num_tiles == 0))
        if ok:
            return NamedSharding(mesh, P(*([None] * ax + [TILE_AXIS])))
        return NamedSharding(mesh, P())

    return spec_for


def shard_pytree(tree: Any, mesh: Mesh, num_tiles: int) -> Any:
    """Place a pytree (SimState / TraceArrays) onto the mesh, tile-sharded."""
    spec = tile_sharding(mesh, num_tiles)

    def place(path, leaf):
        name = ""
        for p in reversed(path):
            if hasattr(p, "name"):
                name = p.name
                break
        return jax.device_put(leaf, spec(name, leaf))

    return jax.tree_util.tree_map_with_path(place, tree)


# ------------------------------------------------- round 15: resident mode
# (tpu/shard_state = "resident", engine/resident.py): state leaves stay
# SHARDED along the tile axis for the whole run, so shard_map in/out specs
# are per-leaf PartitionSpecs instead of the replicated P() above.  The
# field->axis table is the replicated one plus dram_qacc (the [6, T] DRAM
# moment accumulators never cross the replicated path's shard_map seam, so
# the round-11 table omits them).
_RESIDENT_EXTRA_AXES = {"dram_qacc": 1}


def _path_name(path) -> str:
    for p in reversed(path):
        if hasattr(p, "name"):
            return p.name
    return ""


def resident_spec_for_shape(name: str, shape, num_tiles: int):
    """PartitionSpec of one leaf SHAPE under resident sharding: tile axis
    split over the mesh, everything else (scalars, sync objects,
    zero-size compiled-out arrays) replicated."""
    ax = _TILE_AXIS_BY_FIELD.get(name, _RESIDENT_EXTRA_AXES.get(name, 0))
    ok = len(shape) > ax and (
        shape[ax] == num_tiles
        or (name in _TILE_MAJOR_FLAT and shape[ax] % num_tiles == 0
            and shape[ax] > 0))
    if ok:
        return P(*([None] * ax + [TILE_AXIS]))
    return P()


def resident_spec_for(name: str, leaf: Any, num_tiles: int):
    """PartitionSpec of one leaf under resident sharding."""
    return resident_spec_for_shape(name, np.shape(leaf), num_tiles)


def resident_specs(tree: Any, num_tiles: int) -> Any:
    """Matching pytree of PartitionSpecs for ``tree`` (SimState /
    TraceArrays / any container of named leaves) under resident
    sharding — the in_specs/out_specs form shard_map wants."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: resident_spec_for(_path_name(path), leaf,
                                             num_tiles),
        tree)


def resident_place(tree: Any, mesh: Mesh, num_tiles: int) -> Any:
    """device_put a pytree onto the mesh with resident (tile-sharded)
    placement — the driver-entry placement for resident runs."""

    def place(path, leaf):
        spec = resident_spec_for(_path_name(path), leaf, num_tiles)
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, tree)
