"""Module-filtered logging (the reference Log framework's role).

The reference's Log singleton (reference: common/misc/log.h, [log] config
carbon_sim.cfg:75-79) offers per-module enable/disable lists, per-tile log
files, and LOG_PRINT/LOG_ASSERT_ERROR macros compiled out unless enabled.
In a jitted array engine, per-event device logging is not meaningful —
state machines advance thousands of tiles per fused step — so the same
capability maps to:

  * host-side module-filtered loggers for everything that runs on the
    host (driver loop, config resolution, CLI, trace IO), configured from
    the same [log] keys;
  * ``log_assert`` for fail-loudly invariant checks on host values
    (LOG_ASSERT_ERROR's role);
  * device-side inspection is the summary/statistics-trace machinery
    (engine/sim.py) rather than print streams.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

_CONFIGURED = False
_ENABLED: Optional[set] = None     # None = all modules when enabled
_DISABLED: set = set()
_ROOT = "graphite_tpu"


def _apply_filter(module: str, lg: logging.Logger) -> None:
    if module in _DISABLED or (_ENABLED is not None
                               and module not in _ENABLED):
        lg.setLevel(logging.CRITICAL)
    else:
        lg.setLevel(logging.NOTSET)     # inherit the root's level


def configure(cfg) -> None:
    """Apply the [log] config section (reference: log.cc reading
    log/enabled_modules + log/disabled_modules).  Re-applies the filter to
    every already-created module logger, so loggers fetched at import time
    (before configure) pick up the new policy."""
    global _CONFIGURED, _ENABLED, _DISABLED
    enabled = cfg.get_bool("log/enabled", False)
    mods = [m.strip() for m in
            cfg.get_str("log/enabled_modules", "").split(",") if m.strip()]
    dis = [m.strip() for m in
           cfg.get_str("log/disabled_modules", "").split(",") if m.strip()]
    _ENABLED = set(mods) if mods else None
    _DISABLED = set(dis)
    root = logging.getLogger(_ROOT)
    root.setLevel(logging.DEBUG if enabled else logging.WARNING)
    if not _CONFIGURED:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "[%(name)s] %(levelname)s %(message)s"))
        root.addHandler(h)
        _CONFIGURED = True
    prefix = _ROOT + "."
    for name, lg in logging.Logger.manager.loggerDict.items():
        if name.startswith(prefix) and isinstance(lg, logging.Logger):
            _apply_filter(name[len(prefix):], lg)


def get_logger(module: str) -> logging.Logger:
    """Per-module logger honoring the enable/disable lists."""
    lg = logging.getLogger(f"{_ROOT}.{module}")
    _apply_filter(module, lg)
    return lg


def log_assert(condition: bool, message: str, *args) -> None:
    """LOG_ASSERT_ERROR's role: loud, formatted invariant failure."""
    if not condition:
        raise AssertionError(message % args if args else message)
