"""Analytic energy/power/area accounting (the McPAT + CACTI + DSENT role).

The reference drives ~65k LoC of third-party engines through thin
interfaces whose *shape* is: per-component event counters x per-event
energy costs, plus leakage power x time, with technology-node and
DVFS voltage/frequency scaling (reference:
common/mcpat/mcpat_core_interface.h:80-99 — per-instruction micro-op
event counts in, {area, leakage_energy, dynamic_energy} per component
out; contrib/dsent/ for per-flit router/link energies;
common/tile/tile_energy_monitor.cc for the periodic roll-up).

Here the same capability is a closed-form table model evaluated on the
engine's existing Counters — no RTL-calibrated engine is ported (the
constants are order-of-magnitude analytic stand-ins, documented per
component), but every scaling *behavior* the reference exposes is
modeled:

  * dynamic energy  = events x E_event(component) x (V / V_nom)^2
  * leakage power   = P_leak(component) x V / V_nom, integrated over the
    run's completion time
  * technology scaling across 45/32/22 nm (dynamic energy ~ node^2 from
    capacitance, leakage mildly rising as nodes shrink)
  * DVFS voltage levels: discrete (voltage, max-frequency-factor) tables
    per node — the voltage needed for a module's current frequency is
    the lowest level that still supports it (reference:
    technology/dvfs_levels_{45,32,22}nm.cfg, dvfs_manager.cc) —
    frequencies above the top level's reach raise ConfigError.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict

import numpy as np

from graphite_tpu.config import ConfigError
from graphite_tpu.isa import DVFSModule

# Discrete DVFS levels per technology node: (voltage V, max-frequency
# factor).  Same tables as the reference's technology/dvfs_levels_*.cfg
# (physical V/f operating points, quoted as data).
DVFS_LEVELS = {
    45: ((1.1, 1.0), (1.06, 0.88), (1.02, 0.77), (0.98, 0.65),
         (0.94, 0.54), (0.9, 0.42)),
    32: ((1.1, 1.0), (1.04, 0.87), (0.98, 0.75), (0.92, 0.62),
         (0.86, 0.49), (0.8, 0.36)),
    22: ((1.0, 1.0), (0.96, 0.87), (0.92, 0.75), (0.88, 0.63),
         (0.84, 0.5), (0.8, 0.37)),
}


def nominal_voltage(tech_nm: int) -> float:
    return DVFS_LEVELS[_node(tech_nm)][0][0]


def _node(tech_nm: int) -> int:
    if tech_nm not in DVFS_LEVELS:
        raise ConfigError(
            f"general/technology_node {tech_nm} has no DVFS level table "
            f"(supported: {sorted(DVFS_LEVELS)})")
    return tech_nm


def voltage_for_frequency(freq_ghz, max_freq_ghz: float,
                          tech_nm: int) -> np.ndarray:
    """Lowest level voltage supporting ``freq_ghz`` (elementwise).

    Mirrors DVFSManager's level lookup (dvfs_manager.cc getVoltage): each
    level's reach is factor * max_frequency; running faster than the top
    level supports is a config error.
    """
    levels = DVFS_LEVELS[_node(tech_nm)]
    f = np.asarray(freq_ghz, dtype=np.float64)
    v = np.full(f.shape, np.nan)
    # 1% relative tolerance: engine frequencies are derived from integer
    # ps periods (period = round(1000/f)), which perturbs them by up to
    # ~0.25% — far above float epsilon, far below the >=10% spacing of
    # adjacent levels, so a module configured exactly at a level boundary
    # stays on its level instead of tripping the next one (or the error).
    for volt, factor in levels:           # descending reach
        v = np.where(f <= factor * max_freq_ghz * 1.01, volt, v)
    if np.isnan(v).any():
        raise ConfigError(
            f"frequency {float(np.max(f)):.3f} GHz exceeds the "
            f"{_node(tech_nm)}nm top DVFS level "
            f"({levels[0][1] * max_freq_ghz:.3f} GHz)")
    return v


# ---------------------------------------------------------------- tables
# Per-event dynamic energies in pJ at 45 nm / nominal voltage, and
# per-component leakage in mW.  Analytic stand-ins at published orders of
# magnitude (a 45nm ALU op is a few pJ; SRAM reads grow ~sqrt(size);
# 2D-mesh router+link flit traversal ~1-2 pJ; DRAM tens of pJ/byte).

_E_INST_PJ = 6.0          # mean per-instruction core energy (fetch+decode+ex)
_E_BRANCH_PJ = 2.0        # predictor + redirect overhead
_E_DIR_PJ = 4.0           # directory/slice tag+bitmap update
_E_DRAM_PJ_PER_BYTE = 25.0
_E_ROUTER_FLIT_PJ = 1.2   # per-flit per-hop router traversal (DSENT-shaped)
_E_LINK_FLIT_PJ = 0.8     # per-flit per-hop link traversal
_LEAK_CORE_MW = 8.0
_LEAK_CACHE_MW_PER_KB = 0.06
_LEAK_ROUTER_MW = 1.5

# Dynamic energy ~ C V^2: capacitance shrinks ~linearly per node step,
# V^2 from the node's nominal voltage; leakage density RISES as nodes
# shrink (subthreshold), net per-tile leakage roughly flat-to-down.
_NODE_DYN = {45: 1.0, 32: 0.60, 22: 0.38}
_NODE_LEAK = {45: 1.0, 32: 0.85, 22: 0.75}


def _cache_access_pj(size_kb: int, assoc: int, banks: int = 1) -> float:
    """CACTI-shaped SRAM access energy: grows with sqrt(capacity) and
    mildly with associativity (more ways read per access).  Banking cuts
    dynamic access energy — each access activates one bank of
    size/banks — at an area premium (CACTI's banked organization; the
    [cache]/num_banks knob the reference feeds McPAT)."""
    banks = max(banks, 1)
    return 0.4 * math.sqrt(max(size_kb / banks, 1)) \
        * (1.0 + 0.08 * assoc)


def _cache_area_mm2(size_kb: int, tech_nm: int, banks: int = 1) -> float:
    """~0.25 mm^2 per 256KB at 45nm, scaling with node^2; each extra bank
    adds ~3% periphery overhead (decoders/sense amps per bank)."""
    return 0.25 * (size_kb / 256.0) * (tech_nm / 45.0) ** 2 \
        * (1.0 + 0.03 * (max(banks, 1) - 1))


@dataclasses.dataclass(frozen=True)
class EnergyBreakdown:
    """Per-tile energy arrays in joules ([T] float64 each) + static area."""

    core: np.ndarray
    l1i: np.ndarray
    l1d: np.ndarray
    l2: np.ndarray
    directory: np.ndarray
    dram: np.ndarray
    network: np.ndarray
    leakage: np.ndarray
    area_mm2_per_tile: float

    @property
    def dynamic_total(self) -> np.ndarray:
        return (self.core + self.l1i + self.l1d + self.l2 + self.directory
                + self.dram + self.network)

    @property
    def total(self) -> np.ndarray:
        return self.dynamic_total + self.leakage

    def to_dict(self) -> Dict:
        d = {f.name: float(getattr(self, f.name).sum())
             for f in dataclasses.fields(self)
             if f.name != "area_mm2_per_tile"}
        d["dynamic_total"] = float(self.dynamic_total.sum())
        d["total"] = float(self.total.sum())
        d["area_mm2_per_tile"] = self.area_mm2_per_tile
        return d


def compute_energy(params, counters: Dict[str, np.ndarray],
                   completion_time_ps: int,
                   period_ps: np.ndarray) -> EnergyBreakdown:
    """Evaluate the table model on final counters.

    ``period_ps``: [T, NUM_DVFS_MODULES] int32 — each module's current
    clock period; its frequency selects the discrete voltage level whose
    square scales that module's dynamic energy (the same counters-x-
    energy-at-current-V/f evaluation McPATCoreInterface performs on its
    event counts, mcpat_core_interface.h:96-99).
    """
    tech = params.technology_node
    dyn = _NODE_DYN[_node(tech)]
    leak_f = _NODE_LEAK[_node(tech)]
    vnom = nominal_voltage(tech)
    freq = 1000.0 / np.maximum(np.asarray(period_ps, np.float64), 1.0)
    volt = voltage_for_frequency(freq, params.max_frequency_ghz, tech)
    vf2 = (volt / vnom) ** 2               # [T, M] per-module V^2 scale

    def vm(module: DVFSModule) -> np.ndarray:
        return vf2[:, int(module)]

    c = {k: np.asarray(v, np.float64) for k, v in counters.items()}
    pj = 1e-12 * dyn

    core = pj * vm(DVFSModule.CORE) * (
        _E_INST_PJ * c["icount"] + _E_BRANCH_PJ * c["branches"])
    e_l1i = _cache_access_pj(params.l1i.size_kb, params.l1i.associativity,
                             params.l1i.num_banks)
    e_l1d = _cache_access_pj(params.l1d.size_kb, params.l1d.associativity,
                             params.l1d.num_banks)
    e_l2 = _cache_access_pj(params.l2.size_kb, params.l2.associativity,
                            params.l2.num_banks)
    l1i = pj * vm(DVFSModule.L1_ICACHE) * e_l1i * c["l1i_access"]
    l1d = pj * vm(DVFSModule.L1_DCACHE) * e_l1d * (
        c["l1d_read"] + c["l1d_write"])
    l2 = pj * vm(DVFSModule.L2_CACHE) * e_l2 * c["l2_access"]
    directory = pj * vm(DVFSModule.DIRECTORY) * _E_DIR_PJ * (
        c["dir_sh_req"] + c["dir_ex_req"] + c["dir_invalidations"])
    dram = pj * _E_DRAM_PJ_PER_BYTE * params.line_size * (
        c["dram_reads"] + c["dram_writes"])
    # Flit counters tally injections; each flit traverses ~mean-hop-count
    # routers+links (2/3 of the mesh span per dimension for uniform
    # traffic) — the aggregate form of DSENT's per-hop energies.
    mean_hops = max(1.0, (params.mesh_width + params.mesh_height) / 3.0)
    e_hop = (_E_ROUTER_FLIT_PJ + _E_LINK_FLIT_PJ) * mean_hops
    network = pj * e_hop * (
        vm(DVFSModule.NETWORK_MEMORY) * c["net_mem_flits"]
        + vm(DVFSModule.NETWORK_USER) * c["net_user_flits"])

    # Leakage: P x V/Vnom x time (reference computes leakage energy per
    # interval at current voltage).
    seconds = completion_time_ps * 1e-12
    cache_kb = (params.l1i.size_kb + params.l1d.size_kb
                + (0 if params.shared_l2 else params.l2.size_kb))
    slice_kb = params.l2.size_kb if params.shared_l2 else 0
    leak_mw = (_LEAK_CORE_MW
               + _LEAK_CACHE_MW_PER_KB * (cache_kb + slice_kb)
               + _LEAK_ROUTER_MW)
    vscale = volt[:, int(DVFSModule.CORE)] / vnom
    leakage = leak_f * leak_mw * 1e-3 * seconds * vscale \
        * np.ones_like(core)

    area = (2.0 * (tech / 45.0) ** 2            # core + router
            + _cache_area_mm2(params.l1i.size_kb, tech,
                              params.l1i.num_banks)
            + _cache_area_mm2(params.l1d.size_kb, tech,
                              params.l1d.num_banks)
            + _cache_area_mm2(params.l2.size_kb, tech,
                              params.l2.num_banks))
    return EnergyBreakdown(core=core, l1i=l1i, l1d=l1d, l2=l2,
                           directory=directory, dram=dram, network=network,
                           leakage=leakage, area_mm2_per_tile=area)


# Sampled-series rows produced by quantum._maybe_sample (stat_scalars):
# indices of the energy-bearing aggregates the power trace consumes.
_PT_ICOUNT, _PT_MEM_FLITS, _PT_USER_FLITS = 0, 1, 2
_PT_DRAM_RD, _PT_DRAM_WR = 3, 4
_PT_L1I, _PT_L1D, _PT_L2, _PT_BRANCH, _PT_DIR = 8, 9, 10, 11, 12


def power_trace(params, stat_time: np.ndarray, stat_scalars: np.ndarray,
                num_samples: int) -> Dict[str, np.ndarray]:
    """Per-interval power from the periodic counter samples — the
    reference's [runtime_energy_modeling/power_trace] file
    (carbon_sim.cfg:141-145; TileEnergyMonitor computes per-interval
    energy the same counters-times-costs way).

    Returns {"time_ns", "dynamic_w", "leakage_w", "total_w"}, one row per
    sample interval (diffs of consecutive samples).  Voltages are taken
    at the configured initial DVFS levels — the sampled series are
    aggregates, so per-sample per-module voltage reconstruction is out of
    scope (a DVFS_SET mid-run shifts the true dynamic power of later
    intervals by the V^2 ratio; documented approximation).
    """
    n = int(num_samples)
    if n < 2:
        return {"time_ns": np.zeros(0), "dynamic_w": np.zeros(0),
                "leakage_w": np.zeros(0), "total_w": np.zeros(0)}
    t = np.asarray(stat_time[:n], np.float64)           # ps
    s = np.asarray(stat_scalars[:, :n], np.float64)
    dt_s = np.maximum(np.diff(t), 1.0) * 1e-12
    d = np.diff(s, axis=1)

    tech = params.technology_node
    dyn = _NODE_DYN[_node(tech)]
    vnom = nominal_voltage(tech)

    def vm2(module: DVFSModule) -> float:
        """(V/Vnom)^2 at the module's initial DVFS frequency — the same
        scaling compute_energy applies per module (energy.py:181-186),
        evaluated at the configured starting levels."""
        v = float(voltage_for_frequency(
            np.asarray(params.module_freq_ghz(module)),
            params.max_frequency_ghz, tech))
        return (v / vnom) ** 2

    e_l1i = _cache_access_pj(params.l1i.size_kb, params.l1i.associativity,
                             params.l1i.num_banks)
    e_l1d = _cache_access_pj(params.l1d.size_kb, params.l1d.associativity,
                             params.l1d.num_banks)
    e_l2 = _cache_access_pj(params.l2.size_kb, params.l2.associativity,
                            params.l2.num_banks)
    mean_hops = max(1.0, (params.mesh_width + params.mesh_height) / 3.0)
    e_hop = (_E_ROUTER_FLIT_PJ + _E_LINK_FLIT_PJ) * mean_hops
    de_pj = dyn * (
        vm2(DVFSModule.CORE) * (_E_INST_PJ * d[_PT_ICOUNT]
                                + _E_BRANCH_PJ * d[_PT_BRANCH])
        + vm2(DVFSModule.L1_ICACHE) * e_l1i * d[_PT_L1I]
        + vm2(DVFSModule.L1_DCACHE) * e_l1d * d[_PT_L1D]
        + vm2(DVFSModule.L2_CACHE) * e_l2 * d[_PT_L2]
        + vm2(DVFSModule.DIRECTORY) * _E_DIR_PJ * d[_PT_DIR]
        + _E_DRAM_PJ_PER_BYTE * params.line_size
        * (d[_PT_DRAM_RD] + d[_PT_DRAM_WR])
        + e_hop * (vm2(DVFSModule.NETWORK_MEMORY) * d[_PT_MEM_FLITS]
                   + vm2(DVFSModule.NETWORK_USER) * d[_PT_USER_FLITS]))
    dynamic_w = de_pj * 1e-12 / dt_s

    leak_f = _NODE_LEAK[_node(tech)]
    cache_kb = (params.l1i.size_kb + params.l1d.size_kb
                + params.l2.size_kb)
    vscale = math.sqrt(vm2(DVFSModule.CORE))
    leak_w_tile = leak_f * 1e-3 * vscale * (
        _LEAK_CORE_MW + _LEAK_CACHE_MW_PER_KB * cache_kb + _LEAK_ROUTER_MW)
    leakage_w = np.full_like(dynamic_w, leak_w_tile * params.num_tiles)
    return {
        "time_ns": t[1:] * 1e-3,
        "dynamic_w": dynamic_w,
        "leakage_w": leakage_w,
        "total_w": dynamic_w + leakage_w,
    }
