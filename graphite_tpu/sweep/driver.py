"""Sweep request queue: bucket submissions, pad, batch, account compiles.

The engine's compile cost is per PROGRAM, not per design point: a sweep
batch's jit key is (canonical structural params, batch width V, trace
shape).  This driver keeps that cache bounded and observable:

  * submissions queue up and are grouped by STRUCTURAL SIGNATURE
    (sweep/space.py) — variants that could not share a program never
    land in one batch;
  * each bucket pads to a power-of-two V (repeating its last variant) so
    arbitrary submission counts collapse onto log2-many batch widths —
    3, 5, or 7 variants all run the V=8 program;
  * a compile-accounting assertion: draining a bucket whose
    (signature, V) shape already compiled this process must NOT compile
    again (batch.compile_count() is bumped per jit trace, i.e. per
    in-process compile request).  A violation means variant values
    leaked into the static argument — the exact regression the
    canonical-params design exists to prevent.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from graphite_tpu.engine.sim import SimSummary
from graphite_tpu.events.schema import Trace
from graphite_tpu.params import SimParams
from graphite_tpu.sweep import batch as batchmod
from graphite_tpu.sweep.batch import SweepSimulator
from graphite_tpu.sweep.space import structural_signature


def _ceil_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class SweepDriver:
    """Queue variants of ONE trace; drain them as padded vmapped batches.

    Usage::

        drv = SweepDriver(trace)
        tickets = [drv.submit(p) for p in variant_params_list]
        results = drv.drain()          # {ticket: SimSummary}
    """

    def __init__(self, trace: Trace, max_steps: Optional[int] = None,
                 poll_every: int = 8):
        self.trace = trace
        self.max_steps = max_steps
        self.poll_every = poll_every
        self._pending: List[Tuple[int, SimParams]] = []
        self._next_ticket = 0
        # (structural signature, padded V) shapes already compiled by
        # THIS driver's process — the compile-cache bound being asserted.
        self._compiled_shapes: set = set()
        self.compiles_observed = 0
        # Results of buckets that COMPLETED during a drain that later
        # raised: their tickets already left the queue, so the results
        # must survive to the retry drain instead of vanishing with the
        # exception (the drain() docstring's promise, now kept).
        self._completed: Dict[int, SimSummary] = {}

    def submit(self, params: SimParams) -> int:
        """Queue one variant; returns a ticket redeemable at drain()."""
        ticket = self._next_ticket
        self._next_ticket += 1
        self._pending.append((ticket, params))
        return ticket

    def pending(self) -> int:
        return len(self._pending)

    def drain(self) -> Dict[int, SimSummary]:
        """Run every queued variant; {ticket: SimSummary}.  Buckets run
        in first-submission order; within a bucket, results keep
        submission order (padding lanes are dropped).  Submissions leave
        the queue only as their bucket COMPLETES — a mid-drain failure
        (a DeadlockError in one bucket) leaves the failed and not-yet-run
        buckets queued for a retry drain instead of discarding them;
        buckets that completed BEFORE the failure are stashed and
        returned by that retry drain (their tickets stay redeemable)."""
        buckets: Dict[tuple, List[Tuple[int, SimParams]]] = {}
        order: List[tuple] = []
        for ticket, p in self._pending:
            sig = structural_signature(p)
            if sig not in buckets:
                buckets[sig] = []
                order.append(sig)
            buckets[sig].append((ticket, p))

        for sig in order:
            items = buckets[sig]
            v = len(items)
            vpad = _ceil_pow2(v)
            variants = [p for _, p in items]
            # Pad with copies of the last variant: identical timing math,
            # lanes discarded below — the pow2 width is what bounds the
            # compile cache.
            variants += [variants[-1]] * (vpad - v)
            shape_key = (sig, vpad, self.trace.ops.shape)
            before = batchmod.compile_count()
            sim = SweepSimulator(variants, self.trace)
            summaries = sim.run(max_steps=self.max_steps,
                                poll_every=self.poll_every)
            compiled = batchmod.compile_count() - before
            self.compiles_observed += compiled
            if shape_key in self._compiled_shapes and compiled != 0:
                raise AssertionError(
                    f"sweep bucket shape recompiled ({compiled} traces) "
                    f"although (signature, V={vpad}) already compiled — "
                    f"variant values leaked into the jit-static argument")
            if compiled > 1:
                raise AssertionError(
                    f"sweep bucket compiled {compiled} programs; the "
                    f"batched megarun must compile exactly once per "
                    f"bucket shape")
            self._compiled_shapes.add(shape_key)
            done_tickets = set()
            for (ticket, _), summary in zip(items, summaries[:v]):
                self._completed[ticket] = summary
                done_tickets.add(ticket)
            self._pending = [(t, p) for t, p in self._pending
                             if t not in done_tickets]
        results = dict(self._completed)
        self._completed.clear()
        return results
