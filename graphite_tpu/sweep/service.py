"""Fault-tolerant sweep service: crash-safe tickets over the sweep engine.

The SweepDriver (sweep/driver.py) is a correct but fragile batcher: one
poisoned lane sinks its whole padded bucket, a crash loses every queued
ticket, and nothing survives the process.  This module is the ROADMAP's
"sweep-as-a-service" layer made safe to lean on — the four pillars of
ISSUE 15:

  1. **Ticket lifecycle + durable journal.**  Tickets move through
     QUEUED / RUNNING / DONE / FAILED / QUARANTINED.  Every transition
     is appended to a journal directory as its own JSON record, written
     atomically (tmp + fsync + rename, the events/trace_cache.py
     pattern) — a crash between any two syscalls leaves a replayable
     prefix, never a torn record.  A restarted service replays the
     journal: DONE tickets are never re-run, in-flight (RUNNING) work is
     re-queued or resumed from its preemption checkpoint.
  2. **Poison-lane isolation.**  A bucket that raises (DeadlockError or
     an injected fault) is retried with exponential backoff — transient
     faults clear — then BISECTED: halves re-run until the failing
     variant is isolated, which is QUARANTINED with its error attached
     while every healthy lane is served.  Bisection recurses over the
     REAL tickets and re-pads each half, so a fault in a padding lane
     (a copy of the last real variant) quarantines that real ticket
     exactly once.
  3. **Preempt / checkpoint / resume.**  Buckets run under an optional
     wall-clock budget; on expiry the batched [V]-leading state is
     checkpointed (schema v25, engine/checkpoint.py) at a window
     boundary and the bucket resumes — in this process or after a
     restart — bit-identically per lane.  A corrupt checkpoint
     (CheckpointCorruptError) is discarded and the bucket re-runs from
     scratch: the journal, not the checkpoint, is the source of truth.
  4. **Serve-from-cache tier.**  tools/results_db.py doubles as a
     persistent result cache keyed on (structural signature, variant
     signature, trace content hash): re-submitting an already-completed
     design point returns the stored summary with zero compiles and
     zero simulated windows.

One service process owns one journal directory at a time (no
cross-process locking — the deployment story is one serving process per
queue, restarted by a supervisor).  The fault-injection harness
(graphite_tpu/testing/faults.py) reaches every failure path above from
tests and the run_tests.sh kill-and-recover gate.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from graphite_tpu.config import Config, load_config
from graphite_tpu.engine.checkpoint import CheckpointCorruptError
from graphite_tpu.engine.sim import DeadlockError
from graphite_tpu.events.schema import Trace
from graphite_tpu.params import SimParams
from graphite_tpu.sweep import batch as batchmod
from graphite_tpu.sweep.batch import SweepSimulator
from graphite_tpu.sweep.driver import _ceil_pow2
from graphite_tpu.sweep.space import (structural_signature, variant_label,
                                      variant_signature)
from graphite_tpu.testing.faults import FaultInjected

__all__ = ["SweepService", "Ticket", "QUEUED", "RUNNING", "DONE",
           "FAILED", "QUARANTINED"]

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"          # transient failure exhausted its retries
QUARANTINED = "quarantined"  # config-attributed: isolated by bisection

TERMINAL = frozenset({DONE, FAILED, QUARANTINED})


@dataclass
class Ticket:
    """One queued design point.  Durable identity is the OVERRIDES dict
    (JSON-able config paths -> values) — params are rebuilt from the
    journal's base config on restart, never serialized."""

    ticket: int
    overrides: Dict[str, str]
    label: str
    status: str = QUEUED
    summary: Optional[dict] = None
    error: Optional[str] = None
    from_cache: bool = False
    params: Optional[SimParams] = field(default=None, repr=False)


def _atomic_write_json(path: str, obj) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.json")
    pending = tmp
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        pending = None
    finally:
        if pending is not None:
            try:
                os.unlink(pending)
            except OSError:
                pass


_results_db_mod = None


def _results_db():
    """tools/results_db.py, loaded by path (tools/ is not a package);
    None when the tree ships without it — the cache tier then simply
    stays cold."""
    global _results_db_mod
    if _results_db_mod is None:
        import importlib.util
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools", "results_db.py")
        if not os.path.exists(path):
            return None
        spec = importlib.util.spec_from_file_location(
            "graphite_tpu_results_db", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _results_db_mod = mod
    return _results_db_mod


class SweepService:
    """Crash-safe ticket queue over SweepSimulator buckets.

    Usage::

        svc = SweepService(trace, journal_dir, cfg=cfg, db_path=db)
        for overrides in points:
            svc.submit(overrides)
        tickets = svc.serve()        # {id: Ticket}, all terminal or
                                     # preempted-resumable

    Restarting with the same journal_dir replays the journal: DONE
    tickets keep their summaries, RUNNING tickets resume from their
    preemption checkpoint or re-queue, QUEUED tickets run.
    """

    def __init__(self, trace: Trace, journal_dir: str,
                 cfg: Optional[Config] = None,
                 db_path: Optional[str] = None,
                 budget_s: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 poll_every: Optional[int] = None,
                 max_steps: Optional[int] = None,
                 sleep=time.sleep):
        from graphite_tpu.log import get_logger
        self._lg = get_logger("service")
        self.trace = trace
        self.trace_hash = trace.content_hash()
        self.journal_dir = os.path.abspath(journal_dir)
        os.makedirs(self.journal_dir, exist_ok=True)
        cfg = cfg if cfg is not None else load_config()
        meta_path = os.path.join(self.journal_dir, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("trace_hash") != self.trace_hash:
                raise ValueError(
                    f"journal {self.journal_dir!r} was recorded for a "
                    f"different trace (hash "
                    f"{meta.get('trace_hash', '?')[:12]} != "
                    f"{self.trace_hash[:12]}) — one journal serves one "
                    f"workload")
            # The journal's base config wins: tickets are override
            # DELTAS, so replaying them against a different base would
            # silently rewrite every recovered design point.
            self.cfg = Config.from_text(meta["base_config"])
        else:
            self.cfg = cfg.copy()
            _atomic_write_json(meta_path, {
                "trace_hash": self.trace_hash,
                "base_config": self.cfg.to_text()})
        c = self.cfg
        self.budget_s = budget_s if budget_s is not None \
            else (c.get_float("service/budget_s", 0.0) or None)
        self.max_retries = max_retries if max_retries is not None \
            else c.get_int("service/max_retries", 2)
        self.backoff_s = backoff_s if backoff_s is not None \
            else c.get_float("service/backoff_ms", 50.0) / 1000.0
        self.poll_every = poll_every if poll_every is not None \
            else c.get_int("service/poll_every", 8)
        self.max_steps = max_steps
        self.db_path = db_path
        self._db = None
        self._sleep = sleep
        self._tickets: Dict[int, Ticket] = {}
        self._next_ticket = 0
        self._seq = 0
        # Preempted buckets awaiting resume: [{tickets, checkpoint,
        # steps}] in preemption order.
        self._resumable: List[dict] = []
        self.compiles_observed = 0
        self.stats = {"buckets_run": 0, "cache_hits": 0, "retries": 0,
                      "bisections": 0, "preemptions": 0,
                      "quarantined": 0, "failed": 0,
                      "checkpoints_discarded": 0, "recovered": 0}
        self._recover()

    # ------------------------------------------------------------ journal

    def _journal(self, event: str, **fields) -> None:
        self._seq += 1
        rec = {"seq": self._seq, "event": event}
        rec.update(fields)
        _atomic_write_json(
            os.path.join(self.journal_dir, f"rec-{self._seq:08d}.json"),
            rec)

    def _recover(self) -> None:
        """Replay the journal into in-memory ticket state.  Record files
        are whole-or-absent (atomic rename), so replay is a straight
        fold in sequence order."""
        names = sorted(n for n in os.listdir(self.journal_dir)
                       if n.startswith("rec-") and n.endswith(".json"))
        recs = []
        for n in names:
            with open(os.path.join(self.journal_dir, n)) as f:
                recs.append(json.load(f))
        recs.sort(key=lambda r: r.get("seq", 0))
        for rec in recs:
            ev = rec.get("event")
            if ev == "submit":
                t = Ticket(ticket=rec["ticket"],
                           overrides=dict(rec["overrides"]),
                           label=rec.get("label", ""))
                self._tickets[t.ticket] = t
            elif ev == "running":
                for tid in rec.get("tickets", ()):
                    if tid in self._tickets:
                        self._tickets[tid].status = RUNNING
            elif ev == "done":
                t = self._tickets.get(rec["ticket"])
                if t is not None:
                    t.status = DONE
                    t.summary = rec.get("summary")
                    t.from_cache = bool(rec.get("from_cache"))
                self._drop_resumable(rec["ticket"])
            elif ev in ("failed", "quarantined"):
                t = self._tickets.get(rec["ticket"])
                if t is not None:
                    t.status = FAILED if ev == "failed" else QUARANTINED
                    t.error = rec.get("error")
                self._drop_resumable(rec["ticket"])
            elif ev == "preempted":
                self._drop_resumable(*rec.get("tickets", ()))
                self._resumable.append({
                    "tickets": list(rec["tickets"]),
                    "checkpoint": rec["checkpoint"],
                    "steps": rec.get("steps", 0)})
            elif ev == "requeued":
                for tid in rec.get("tickets", ()):
                    if tid in self._tickets:
                        self._tickets[tid].status = QUEUED
                self._drop_resumable(*rec.get("tickets", ()))
        if self._tickets:
            self._next_ticket = max(self._tickets) + 1
        if recs:
            self._seq = max(r.get("seq", 0) for r in recs)
        # Resumable buckets whose checkpoint vanished can't resume.
        self._resumable = [r for r in self._resumable
                           if os.path.exists(r["checkpoint"])]
        covered = {tid for r in self._resumable for tid in r["tickets"]}
        # In-flight work with no checkpoint: the process died mid-bucket
        # — re-queue it (crash-safety pillar 1).
        requeue = [t.ticket for t in self._tickets.values()
                   if t.status == RUNNING and t.ticket not in covered]
        if requeue:
            self._journal("requeued", tickets=requeue,
                          reason="recovered in-flight work")
            for tid in requeue:
                self._tickets[tid].status = QUEUED
            self.stats["recovered"] += len(requeue)
        if self._tickets:
            self._lg.info(
                "service recovered %d tickets (%d requeued, %d "
                "resumable buckets) from %s", len(self._tickets),
                len(requeue), len(self._resumable), self.journal_dir)

    def _drop_resumable(self, *tids) -> None:
        tids = set(tids)
        self._resumable = [r for r in self._resumable
                           if not tids & set(r["tickets"])]

    # ------------------------------------------------------------- submit

    def submit(self, overrides: Dict[str, str],
               label: Optional[str] = None) -> int:
        """Queue one design point (config-path override deltas over the
        journal's base config); returns the ticket id.  Params build
        eagerly so malformed overrides fail the submitter, not the
        serving loop."""
        overrides = {k: str(v) for k, v in overrides.items()}
        t = Ticket(ticket=self._next_ticket, overrides=overrides,
                   label=label or variant_label(overrides))
        t.params = self._build_params(overrides)
        self._next_ticket += 1
        self._tickets[t.ticket] = t
        self._journal("submit", ticket=t.ticket, overrides=overrides,
                      label=t.label)
        return t.ticket

    def _build_params(self, overrides: Dict[str, str]) -> SimParams:
        c = self.cfg.copy()
        for k, v in overrides.items():
            c.set(k, v)
        return SimParams.from_config(c, num_tiles=self.trace.num_tiles)

    def _params(self, t: Ticket) -> SimParams:
        if t.params is None:
            t.params = self._build_params(t.overrides)
        return t.params

    # -------------------------------------------------------- cache tier

    def _cache_key(self, params: SimParams) -> str:
        import hashlib

        def digest(sig) -> str:
            return hashlib.sha256(repr(sig).encode()).hexdigest()[:12]

        return (f"svc:{digest(structural_signature(params))}:"
                f"{digest(variant_signature(params))}:"
                f"{self.trace_hash[:12]}")

    def _open_db(self):
        if self.db_path is None:
            return None
        if self._db is None:
            mod = _results_db()
            if mod is None:
                return None
            self._db = mod.open_db(self.db_path)
        return self._db

    def _serve_cached(self, t: Ticket) -> bool:
        db = self._open_db()
        if db is None:
            return False
        key = self._cache_key(self._params(t))
        row = db.execute(
            "SELECT raw_json FROM runs WHERE workload = ? "
            "ORDER BY ts DESC, id DESC LIMIT 1", (key,)).fetchone()
        if row is None:
            return False
        t.status = DONE
        t.summary = json.loads(row[0])
        t.from_cache = True
        self.stats["cache_hits"] += 1
        self._journal("done", ticket=t.ticket, summary=t.summary,
                      from_cache=True)
        return True

    def _store(self, t: Ticket, row: dict) -> None:
        db = self._open_db()
        if db is None:
            return
        mod = _results_db()
        mod.add_run(db, self._cache_key(self._params(t)), row)

    # ------------------------------------------------------------ serving

    def tickets(self) -> Dict[int, Ticket]:
        return dict(self._tickets)

    def open_tickets(self) -> List[Ticket]:
        return [t for t in self._tickets.values()
                if t.status not in TERMINAL]

    def drain(self) -> Dict[int, Ticket]:
        """One full serving pass: resume preempted buckets, serve
        cache hits, run every queued bucket (with retry / bisection /
        quarantine).  Tickets still RUNNING afterwards were preempted
        this pass and have a checkpoint on disk — drain again (or
        serve()) to continue them."""
        for rec in list(self._resumable):
            self._resume_bucket(rec)
        for t in sorted(self._tickets.values(), key=lambda t: t.ticket):
            if t.status == QUEUED:
                self._serve_cached(t)
        queued = [t for t in sorted(self._tickets.values(),
                                    key=lambda t: t.ticket)
                  if t.status == QUEUED]
        buckets: Dict[tuple, List[Ticket]] = {}
        order: List[tuple] = []
        for t in queued:
            sig = structural_signature(self._params(t))
            if sig not in buckets:
                buckets[sig] = []
                order.append(sig)
            buckets[sig].append(t)
        for sig in order:
            self._run_bucket(buckets[sig])
        return self.tickets()

    def serve(self) -> Dict[int, Ticket]:
        """drain() until every ticket is terminal.  Each pass makes at
        least one window of progress per preempted bucket (the budget
        check sits after the dispatch), so this terminates."""
        while True:
            self.drain()
            if not self.open_tickets():
                return self.tickets()

    # ----------------------------------------------------- bucket running

    def _padded(self, items: List[Ticket]) -> List[SimParams]:
        variants = [self._params(t) for t in items]
        vpad = _ceil_pow2(len(variants))
        return variants + [variants[-1]] * (vpad - len(variants))

    def _mark_running(self, items: List[Ticket]) -> None:
        fresh = [t.ticket for t in items if t.status != RUNNING]
        for t in items:
            t.status = RUNNING
        if fresh:
            self._journal("running", tickets=fresh)

    def _run_bucket(self, items: List[Ticket]) -> None:
        """Run one structural bucket to a terminal or preempted state,
        with bounded retries (exponential backoff) and bisection on
        persistent failure.  Recursion re-pads each half, so padding
        lanes never multiply a quarantine."""
        self._mark_running(items)
        attempt = 0
        while True:
            try:
                sim = SweepSimulator(self._padded(items), self.trace)
                self._execute(items, sim)
                return
            except (DeadlockError, FaultInjected) as e:
                attempt += 1
                if attempt <= self.max_retries:
                    delay = self.backoff_s * (2 ** (attempt - 1))
                    self.stats["retries"] += 1
                    self._lg.warning(
                        "bucket %s failed (%s); retry %d/%d in %.3fs",
                        [t.ticket for t in items], e, attempt,
                        self.max_retries, delay)
                    if delay > 0:
                        self._sleep(delay)
                    continue
                if len(items) > 1:
                    mid = len(items) // 2
                    self.stats["bisections"] += 1
                    self._lg.warning(
                        "bucket %s still failing after %d retries; "
                        "bisecting", [t.ticket for t in items],
                        self.max_retries)
                    self._run_bucket(items[:mid])
                    self._run_bucket(items[mid:])
                    return
                self._terminal_failure(items[0], e)
                return

    def _execute(self, items: List[Ticket], sim: SweepSimulator) -> None:
        before = batchmod.compile_count()
        summaries = sim.run(max_steps=self.max_steps,
                            poll_every=self.poll_every,
                            budget_s=self.budget_s)
        self.compiles_observed += batchmod.compile_count() - before
        self.stats["buckets_run"] += 1
        if sim.preempted:
            self._preempt(items, sim)
            return
        for t, s in zip(items, summaries[:len(items)]):
            self._complete(t, self._summary_row(s))

    def _summary_row(self, s) -> dict:
        row = s.to_dict()
        row["kind"] = "service_ticket"
        # Per-tile final clocks ride the record so per-lane bit-identity
        # is checkable from the stored summary alone (the acceptance
        # unit of the kill-and-recover gate).
        row["clock_ps"] = np.asarray(s.clock).astype(
            np.int64).reshape(-1).tolist()
        return row

    def _complete(self, t: Ticket, row: dict) -> None:
        t.status = DONE
        t.summary = row
        t.from_cache = False
        self._journal("done", ticket=t.ticket, summary=row,
                      from_cache=False)
        self._store(t, row)

    def _terminal_failure(self, t: Ticket, e: Exception) -> None:
        err = f"{type(e).__name__}: {e}"
        t.error = err
        if isinstance(e, FaultInjected) and e.transient:
            # Retries exhausted on a TRANSIENT fault: the config is not
            # proven poisonous — mark failed, not quarantined, so an
            # operator resubmits rather than blacklists.
            t.status = FAILED
            self.stats["failed"] += 1
            self._journal("failed", ticket=t.ticket, error=err)
        else:
            t.status = QUARANTINED
            self.stats["quarantined"] += 1
            self._journal("quarantined", ticket=t.ticket, error=err)
        self._lg.error("ticket %d (%s) %s: %s", t.ticket, t.label,
                       t.status, err)

    # --------------------------------------------------- preempt / resume

    def _ckpt_path(self, items: List[Ticket]) -> str:
        return os.path.join(self.journal_dir,
                            f"bucket-{items[0].ticket:08d}"
                            f"x{len(items)}.ckpt.npz")

    def _preempt(self, items: List[Ticket], sim: SweepSimulator) -> None:
        path = self._ckpt_path(items)
        sim.save_checkpoint(path)
        rec = {"tickets": [t.ticket for t in items], "checkpoint": path,
               "steps": sim.steps}
        self._journal("preempted", **rec)
        self._drop_resumable(*rec["tickets"])
        self._resumable.append(rec)
        self.stats["preemptions"] += 1
        self._lg.info("bucket %s preempted at step %d -> %s",
                      rec["tickets"], sim.steps, path)

    def _resume_bucket(self, rec: dict) -> None:
        items = [self._tickets[tid] for tid in rec["tickets"]
                 if tid in self._tickets]
        if not items or all(t.status in TERMINAL for t in items):
            self._resumable.remove(rec)
            return
        self._mark_running(items)
        try:
            sim = SweepSimulator(self._padded(items), self.trace)
            sim.restore_checkpoint(rec["checkpoint"])
        except (CheckpointCorruptError, ValueError) as e:
            # Torn/corrupt (or mismatched) checkpoint: discard it and
            # fall back to a from-scratch run — the journal stays the
            # source of truth, the checkpoint is only an optimization.
            self._lg.warning("discarding checkpoint %s (%s); re-running "
                             "bucket %s from scratch", rec["checkpoint"],
                             e, rec["tickets"])
            self.stats["checkpoints_discarded"] += 1
            self._drop_resumable(*rec["tickets"])
            try:
                os.unlink(rec["checkpoint"])
            except OSError:
                pass
            self._journal("requeued", tickets=rec["tickets"],
                          reason=f"checkpoint corrupt: {e}")
            self._run_bucket(items)
            return
        self._drop_resumable(*rec["tickets"])
        try:
            self._execute(items, sim)
        except (DeadlockError, FaultInjected) as e:
            self._lg.warning("resumed bucket %s failed (%s); re-running "
                             "from scratch", rec["tickets"], e)
            self._run_bucket(items)
            return
        finally:
            # The consumed checkpoint is garbage once the bucket either
            # completed or re-checkpointed under a new path/record.
            if not any(r["checkpoint"] == rec["checkpoint"]
                       for r in self._resumable):
                try:
                    os.unlink(rec["checkpoint"])
                except OSError:
                    pass

    # ------------------------------------------------------------ results

    def result_rows(self) -> Dict[str, dict]:
        """{label: summary row} for every DONE ticket (labels collide
        only when one design point was submitted twice; later tickets
        win, which is also the fresher summary)."""
        out = {}
        for t in sorted(self._tickets.values(), key=lambda t: t.ticket):
            if t.status == DONE and t.summary is not None:
                out[t.label] = t.summary
        return out
