"""Fault-tolerant sweep service: crash-safe tickets over the sweep engine.

The SweepDriver (sweep/driver.py) is a correct but fragile batcher: one
poisoned lane sinks its whole padded bucket, a crash loses every queued
ticket, and nothing survives the process.  This module is the ROADMAP's
"sweep-as-a-service" layer made safe to lean on — the four pillars of
ISSUE 15:

  1. **Ticket lifecycle + durable journal.**  Tickets move through
     QUEUED / RUNNING / DONE / FAILED / QUARANTINED.  Every transition
     is appended to a journal directory as its own JSON record, written
     atomically (tmp + fsync + rename, the events/trace_cache.py
     pattern) — a crash between any two syscalls leaves a replayable
     prefix, never a torn record.  A restarted service replays the
     journal: DONE tickets are never re-run, in-flight (RUNNING) work is
     re-queued or resumed from its preemption checkpoint.
  2. **Poison-lane isolation.**  A bucket that raises (DeadlockError or
     an injected fault) is retried with exponential backoff — transient
     faults clear — then BISECTED: halves re-run until the failing
     variant is isolated, which is QUARANTINED with its error attached
     while every healthy lane is served.  Bisection recurses over the
     REAL tickets and re-pads each half, so a fault in a padding lane
     (a copy of the last real variant) quarantines that real ticket
     exactly once.
  3. **Preempt / checkpoint / resume.**  Buckets run under an optional
     wall-clock budget; on expiry the batched [V]-leading state is
     checkpointed (schema v25, engine/checkpoint.py) at a window
     boundary and the bucket resumes — in this process or after a
     restart — bit-identically per lane.  A corrupt checkpoint
     (CheckpointCorruptError) is discarded and the bucket re-runs from
     scratch: the journal, not the checkpoint, is the source of truth.
  4. **Serve-from-cache tier.**  tools/results_db.py doubles as a
     persistent result cache keyed on (structural signature, variant
     signature, trace content hash): re-submitting an already-completed
     design point returns the stored summary with zero compiles and
     zero simulated windows.

One service process owns one journal directory at a time (no
cross-process locking — the deployment story is one serving process per
queue, restarted by a supervisor).  The fault-injection harness
(graphite_tpu/testing/faults.py) reaches every failure path above from
tests and the run_tests.sh kill-and-recover gate.

**Observability (ISSUE 17).**  Every journal record carries wall
(``ts``) and monotonic (``mono``) timestamps — replay tolerates
pre-ISSUE-17 records without them — and every lifecycle transition
feeds the process-wide metrics registry (obs/registry.py):
``ticket_latency_s`` / ``first_result_latency_s`` histograms,
``variants_served_total``, ``cache_hits_total`` / ``cache_misses_total``
+ the ``cache_hit_ratio`` gauge, per-state ``tickets_in_state`` gauges,
and one ``svc_*_total`` counter per ``stats`` key.  Results STREAM: the
per-lane done poll inside SweepSimulator.run surfaces each lane the
poll it finishes (``first_result`` journal event + ``on_result``
callback + the ticket's summary set) instead of at bucket drain, with
per-drain p50/p99 first-result latency gauges.  ``metrics_path`` writes
the Prometheus exposition atomically after every drain;
``obs.chrome_trace(tracer=..., tickets=svc.tickets().values())``
renders the drain's ticket lifecycles beside the host spans on one
wall-clock timeline.  All of it is host-side bookkeeping: metrics-off
runs remain bit-identical — observability never perturbs simulated
time.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from graphite_tpu.config import Config, load_config
from graphite_tpu.engine.checkpoint import CheckpointCorruptError
from graphite_tpu.engine.sim import DeadlockError
from graphite_tpu.events.schema import Trace
from graphite_tpu.obs.registry import (enable_metrics, get_registry,
                                       write_exposition)
from graphite_tpu.params import SimParams
from graphite_tpu.sweep import batch as batchmod
from graphite_tpu.sweep.batch import SweepSimulator
from graphite_tpu.sweep.driver import _ceil_pow2
from graphite_tpu.sweep.space import (structural_signature, variant_label,
                                      variant_signature)
from graphite_tpu.testing.faults import FaultInjected

__all__ = ["SweepService", "Ticket", "QUEUED", "RUNNING", "DONE",
           "FAILED", "QUARANTINED", "STATES", "read_journal",
           "journal_status"]

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"          # transient failure exhausted its retries
QUARANTINED = "quarantined"  # config-attributed: isolated by bisection

TERMINAL = frozenset({DONE, FAILED, QUARANTINED})
STATES = (QUEUED, RUNNING, DONE, FAILED, QUARANTINED)


@dataclass
class Ticket:
    """One queued design point.  Durable identity is the OVERRIDES dict
    (JSON-able config paths -> values) — params are rebuilt from the
    journal's base config on restart, never serialized.

    ``marks`` holds THIS-process lifecycle timestamps
    (``time.perf_counter()`` seconds: submit / running / first_result /
    done) — the basis of the latency histograms and the Chrome-trace
    ticket track, sharing the SpanTracer's clock.  ``times`` holds the
    wall-clock (``time.time()``) versions, which survive journal replay
    across processes (monotonic clocks don't)."""

    ticket: int
    overrides: Dict[str, str]
    label: str
    status: str = QUEUED
    summary: Optional[dict] = None
    error: Optional[str] = None
    from_cache: bool = False
    params: Optional[SimParams] = field(default=None, repr=False)
    marks: Dict[str, float] = field(default_factory=dict, repr=False)
    times: Dict[str, float] = field(default_factory=dict, repr=False)


def _atomic_write_json(path: str, obj) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.json")
    pending = tmp
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        pending = None
    finally:
        if pending is not None:
            try:
                os.unlink(pending)
            except OSError:
                pass


def read_journal(journal_dir: str) -> List[dict]:
    """All journal records under ``journal_dir``, in sequence order.
    Record files are whole-or-absent (atomic rename), so reading beside
    a live service sees a clean prefix, never a torn record."""
    names = sorted(n for n in os.listdir(journal_dir)
                   if n.startswith("rec-") and n.endswith(".json"))
    recs = []
    for n in names:
        with open(os.path.join(journal_dir, n)) as f:
            recs.append(json.load(f))
    recs.sort(key=lambda r: r.get("seq", 0))
    return recs


def journal_status(journal_dir: str) -> dict:
    """Fold a journal directory into a status view WITHOUT constructing
    a service (no trace, no params): per-state counts plus one row per
    ticket with its wall-clock transition times — the basis of the
    ``status`` CLI subcommand, safe to point at a live service's
    journal.  Latencies derive from the records' wall ``ts`` stamps;
    pre-ISSUE-17 records without them fold into states only."""
    tickets: Dict[int, dict] = {}

    def row(tid: int) -> dict:
        return tickets.setdefault(tid, {
            "ticket": tid, "label": "", "status": QUEUED,
            "from_cache": False, "error": None, "times": {}})

    for rec in read_journal(journal_dir):
        ev, ts = rec.get("event"), rec.get("ts")

        def stamp(r: dict, mark: str) -> None:
            if ts is not None:
                r["times"][mark] = ts

        if ev == "submit":
            r = row(rec["ticket"])
            r["label"] = rec.get("label", "")
            stamp(r, "submit")
        elif ev == "running":
            for tid in rec.get("tickets", ()):
                r = row(tid)
                r["status"] = RUNNING
                stamp(r, "running")
        elif ev == "first_result":
            stamp(row(rec["ticket"]), "first_result")
        elif ev == "done":
            r = row(rec["ticket"])
            r["status"] = DONE
            r["from_cache"] = bool(rec.get("from_cache"))
            stamp(r, "done")
        elif ev in ("failed", "quarantined"):
            r = row(rec["ticket"])
            r["status"] = FAILED if ev == "failed" else QUARANTINED
            r["error"] = rec.get("error")
            stamp(r, "done")
        elif ev == "requeued":
            for tid in rec.get("tickets", ()):
                row(tid)["status"] = QUEUED

    counts = {s: 0 for s in STATES}
    for r in tickets.values():
        counts[r["status"]] += 1

    def pct(vals: List[float], q: float) -> Optional[float]:
        return float(np.percentile(np.asarray(vals), q)) if vals else None

    first = [r["times"]["first_result"] - r["times"]["submit"]
             for r in tickets.values()
             if "first_result" in r["times"] and "submit" in r["times"]]
    done = [r["times"]["done"] - r["times"]["submit"]
            for r in tickets.values()
            if r["status"] == DONE and "done" in r["times"]
            and "submit" in r["times"]]
    return {
        "journal_dir": os.path.abspath(journal_dir),
        "tickets": [tickets[tid] for tid in sorted(tickets)],
        "counts": counts,
        "open": counts[QUEUED] + counts[RUNNING],
        "p50_first_result_s": pct(first, 50),
        "p99_first_result_s": pct(first, 99),
        "p50_ticket_latency_s": pct(done, 50),
        "p99_ticket_latency_s": pct(done, 99),
    }


_results_db_mod = None


def _results_db():
    """tools/results_db.py, loaded by path (tools/ is not a package);
    None when the tree ships without it — the cache tier then simply
    stays cold."""
    global _results_db_mod
    if _results_db_mod is None:
        import importlib.util
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools", "results_db.py")
        if not os.path.exists(path):
            return None
        spec = importlib.util.spec_from_file_location(
            "graphite_tpu_results_db", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _results_db_mod = mod
    return _results_db_mod


class SweepService:
    """Crash-safe ticket queue over SweepSimulator buckets.

    Usage::

        svc = SweepService(trace, journal_dir, cfg=cfg, db_path=db)
        for overrides in points:
            svc.submit(overrides)
        tickets = svc.serve()        # {id: Ticket}, all terminal or
                                     # preempted-resumable

    Restarting with the same journal_dir replays the journal: DONE
    tickets keep their summaries, RUNNING tickets resume from their
    preemption checkpoint or re-queue, QUEUED tickets run.
    """

    def __init__(self, trace: Trace, journal_dir: str,
                 cfg: Optional[Config] = None,
                 db_path: Optional[str] = None,
                 budget_s: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 poll_every: Optional[int] = None,
                 max_steps: Optional[int] = None,
                 metrics_path: Optional[str] = None,
                 on_result=None,
                 sleep=time.sleep):
        from graphite_tpu.log import get_logger
        self._lg = get_logger("service")
        self.trace = trace
        cfg = cfg if cfg is not None else load_config()
        # Streamed submissions (trace/segment_events > 0, round 16) key
        # on the CHAINED per-segment digest (events/segments.py): a
        # capture can be hashed segment-by-segment as it lands, and two
        # submissions with equal streamed hashes simulate bit-identically
        # under equal params (streamed execution == whole-trace is the
        # ingest contract) — so DONE tickets and results_db rows are
        # shared across identical streamed submissions.  Buckets still
        # EXECUTE whole-trace (the sweep engine vmaps one resident
        # trace); the hash is the ticket identity, not the run mode.
        seg = cfg.get_int("trace/segment_events", 0)
        if seg > 0:
            from graphite_tpu.events.segments import streamed_content_hash
            self.trace_hash = streamed_content_hash(trace, seg)
        else:
            self.trace_hash = trace.content_hash()
        self.journal_dir = os.path.abspath(journal_dir)
        os.makedirs(self.journal_dir, exist_ok=True)
        meta_path = os.path.join(self.journal_dir, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("trace_hash") != self.trace_hash:
                raise ValueError(
                    f"journal {self.journal_dir!r} was recorded for a "
                    f"different trace (hash "
                    f"{meta.get('trace_hash', '?')[:12]} != "
                    f"{self.trace_hash[:12]}) — one journal serves one "
                    f"workload")
            # The journal's base config wins: tickets are override
            # DELTAS, so replaying them against a different base would
            # silently rewrite every recovered design point.
            self.cfg = Config.from_text(meta["base_config"])
        else:
            self.cfg = cfg.copy()
            _atomic_write_json(meta_path, {
                "trace_hash": self.trace_hash,
                "base_config": self.cfg.to_text()})
        c = self.cfg
        self.budget_s = budget_s if budget_s is not None \
            else (c.get_float("service/budget_s", 0.0) or None)
        self.max_retries = max_retries if max_retries is not None \
            else c.get_int("service/max_retries", 2)
        self.backoff_s = backoff_s if backoff_s is not None \
            else c.get_float("service/backoff_ms", 50.0) / 1000.0
        self.poll_every = poll_every if poll_every is not None \
            else c.get_int("service/poll_every", 8)
        self.max_steps = max_steps
        self.db_path = db_path
        self._db = None
        self._sleep = sleep
        self._tickets: Dict[int, Ticket] = {}
        self._next_ticket = 0
        self._seq = 0
        # Preempted buckets awaiting resume: [{tickets, checkpoint,
        # steps}] in preemption order.
        self._resumable: List[dict] = []
        self.compiles_observed = 0
        self.stats = {"buckets_run": 0, "cache_hits": 0,
                      "cache_misses": 0, "retries": 0,
                      "bisections": 0, "preemptions": 0,
                      "quarantined": 0, "failed": 0,
                      "checkpoints_discarded": 0, "recovered": 0,
                      "first_results": 0}
        # --- observability: registry handles + callbacks -------------
        self.metrics_path = metrics_path
        self.on_result = on_result   # on_result(ticket, row) at first
        #                              result availability
        if metrics_path:
            enable_metrics(True)
        reg = get_registry()
        self._m_latency = reg.histogram(
            "ticket_latency_s", "submit-to-DONE serving latency")
        self._m_first = reg.histogram(
            "first_result_latency_s",
            "submit-to-first-result latency (streamed lane poll)")
        self._m_served = reg.counter(
            "variants_served_total",
            "tickets served to DONE (simulated or cache)")
        self._m_cache_hits = reg.counter(
            "cache_hits_total", "tickets served from results_db cache")
        self._m_cache_misses = reg.counter(
            "cache_misses_total", "cache lookups that missed")
        self._m_hit_ratio = reg.gauge(
            "cache_hit_ratio", "cache_hits / (hits + misses), lifetime")
        self._m_state = reg.gauge(
            "tickets_in_state", "tickets currently in each lifecycle "
            "state", labels=("state",))
        self._m_drain_p50 = reg.gauge(
            "first_result_latency_p50_s", "per-drain p50 first-result "
            "latency (seconds)")
        self._m_drain_p99 = reg.gauge(
            "first_result_latency_p99_s", "per-drain p99 first-result "
            "latency (seconds)")
        self._first_latencies: List[float] = []
        self._state_counts = {s: 0 for s in STATES}
        for s in STATES:   # zero rows for every state in the exposition
            self._m_state.add(0.0, state=s)
        self._recover()

    # ------------------------------------------------------------ journal

    def _journal(self, event: str, **fields) -> None:
        self._seq += 1
        # Wall + monotonic stamps on every record: the status CLI and
        # cross-restart views read ts; same-process latency/tracing
        # reads mono (perf_counter — the SpanTracer's clock).  Replay
        # tolerates their absence (pre-ISSUE-17 journals).
        rec = {"seq": self._seq, "event": event,
               "ts": time.time(), "mono": time.perf_counter()}
        rec.update(fields)
        _atomic_write_json(
            os.path.join(self.journal_dir, f"rec-{self._seq:08d}.json"),
            rec)

    # -------------------------------------------------------- obs helpers

    def _bump(self, key: str, n: int = 1) -> None:
        """stats[key] += n, mirrored into the svc_<key>_total counter."""
        self.stats[key] += n
        get_registry().counter(
            f"svc_{key}_total", f"service {key} events").inc(n)

    def _set_status(self, t: Ticket, status: str) -> None:
        """Single choke point for status changes: keeps the per-state
        counts (and their gauges) true to the ticket dict."""
        if t.status in self._state_counts:
            self._state_counts[t.status] -= 1
            self._m_state.add(-1.0, state=t.status)
        t.status = status
        self._state_counts[status] += 1
        self._m_state.add(1.0, state=status)

    def _count_ticket(self, t: Ticket) -> None:
        """Account a ticket first entering the dict (already carrying
        its initial status)."""
        self._state_counts[t.status] += 1
        self._m_state.add(1.0, state=t.status)

    def _hit_ratio(self) -> Optional[float]:
        lookups = self.stats["cache_hits"] + self.stats["cache_misses"]
        if lookups == 0:
            return None
        return self.stats["cache_hits"] / lookups

    def _first_result(self, t: Ticket, row: dict) -> None:
        """A ticket's summary became available (lane-done poll or cache
        hit): journal it, observe the first-result latency, stream to
        the on_result callback.  Fires at most once per ticket life."""
        now = time.perf_counter()
        t.summary = row
        t.marks["first_result"] = now
        t.times["first_result"] = time.time()
        self._bump("first_results")
        self._journal("first_result", ticket=t.ticket, summary=row)
        if "submit" in t.marks:
            lat = now - t.marks["submit"]
            self._first_latencies.append(lat)
            self._m_first.observe(lat)
        if self.on_result is not None:
            self.on_result(t, row)

    def _recover(self) -> None:
        """Replay the journal into in-memory ticket state.  Record files
        are whole-or-absent (atomic rename), so replay is a straight
        fold in sequence order.  Timestamps (``ts``) are optional —
        pre-ISSUE-17 journals replay identically, just without times."""
        recs = read_journal(self.journal_dir)

        def stamp(t, mark, rec):
            if rec.get("ts") is not None:
                t.times[mark] = rec["ts"]

        for rec in recs:
            ev = rec.get("event")
            if ev == "submit":
                t = Ticket(ticket=rec["ticket"],
                           overrides=dict(rec["overrides"]),
                           label=rec.get("label", ""))
                self._tickets[t.ticket] = t
                self._count_ticket(t)
                stamp(t, "submit", rec)
            elif ev == "running":
                for tid in rec.get("tickets", ()):
                    if tid in self._tickets:
                        t = self._tickets[tid]
                        self._set_status(t, RUNNING)
                        stamp(t, "running", rec)
            elif ev == "first_result":
                t = self._tickets.get(rec["ticket"])
                if t is not None and t.summary is None:
                    t.summary = rec.get("summary")
                    stamp(t, "first_result", rec)
            elif ev == "done":
                t = self._tickets.get(rec["ticket"])
                if t is not None:
                    self._set_status(t, DONE)
                    t.summary = rec.get("summary")
                    t.from_cache = bool(rec.get("from_cache"))
                    stamp(t, "done", rec)
                self._drop_resumable(rec["ticket"])
            elif ev in ("failed", "quarantined"):
                t = self._tickets.get(rec["ticket"])
                if t is not None:
                    self._set_status(
                        t, FAILED if ev == "failed" else QUARANTINED)
                    t.error = rec.get("error")
                    stamp(t, "done", rec)
                self._drop_resumable(rec["ticket"])
            elif ev == "preempted":
                self._drop_resumable(*rec.get("tickets", ()))
                self._resumable.append({
                    "tickets": list(rec["tickets"]),
                    "checkpoint": rec["checkpoint"],
                    "steps": rec.get("steps", 0)})
            elif ev == "requeued":
                for tid in rec.get("tickets", ()):
                    if tid in self._tickets:
                        self._set_status(self._tickets[tid], QUEUED)
                self._drop_resumable(*rec.get("tickets", ()))
        if self._tickets:
            self._next_ticket = max(self._tickets) + 1
        if recs:
            self._seq = max(r.get("seq", 0) for r in recs)
        # Resumable buckets whose checkpoint vanished can't resume.
        self._resumable = [r for r in self._resumable
                           if os.path.exists(r["checkpoint"])]
        covered = {tid for r in self._resumable for tid in r["tickets"]}
        # In-flight work with no checkpoint: the process died mid-bucket
        # — re-queue it (crash-safety pillar 1).
        requeue = [t.ticket for t in self._tickets.values()
                   if t.status == RUNNING and t.ticket not in covered]
        if requeue:
            self._journal("requeued", tickets=requeue,
                          reason="recovered in-flight work")
            for tid in requeue:
                self._set_status(self._tickets[tid], QUEUED)
            self._bump("recovered", len(requeue))
        if self._tickets:
            self._lg.info(
                "service recovered %d tickets (%d requeued, %d "
                "resumable buckets) from %s", len(self._tickets),
                len(requeue), len(self._resumable), self.journal_dir)

    def _drop_resumable(self, *tids) -> None:
        tids = set(tids)
        self._resumable = [r for r in self._resumable
                           if not tids & set(r["tickets"])]

    # ------------------------------------------------------------- submit

    def submit(self, overrides: Dict[str, str],
               label: Optional[str] = None) -> int:
        """Queue one design point (config-path override deltas over the
        journal's base config); returns the ticket id.  Params build
        eagerly so malformed overrides fail the submitter, not the
        serving loop."""
        overrides = {k: str(v) for k, v in overrides.items()}
        t = Ticket(ticket=self._next_ticket, overrides=overrides,
                   label=label or variant_label(overrides))
        t.params = self._build_params(overrides)
        self._next_ticket += 1
        self._tickets[t.ticket] = t
        t.marks["submit"] = time.perf_counter()
        t.times["submit"] = time.time()
        self._count_ticket(t)
        self._journal("submit", ticket=t.ticket, overrides=overrides,
                      label=t.label)
        return t.ticket

    def _build_params(self, overrides: Dict[str, str]) -> SimParams:
        c = self.cfg.copy()
        for k, v in overrides.items():
            c.set(k, v)
        return SimParams.from_config(c, num_tiles=self.trace.num_tiles)

    def _params(self, t: Ticket) -> SimParams:
        if t.params is None:
            t.params = self._build_params(t.overrides)
        return t.params

    # -------------------------------------------------------- cache tier

    def _cache_key(self, params: SimParams) -> str:
        import hashlib

        def digest(sig) -> str:
            return hashlib.sha256(repr(sig).encode()).hexdigest()[:12]

        return (f"svc:{digest(structural_signature(params))}:"
                f"{digest(variant_signature(params))}:"
                f"{self.trace_hash[:12]}")

    def _open_db(self):
        if self.db_path is None:
            return None
        if self._db is None:
            mod = _results_db()
            if mod is None:
                return None
            self._db = mod.open_db(self.db_path)
        return self._db

    def _serve_cached(self, t: Ticket) -> bool:
        db = self._open_db()
        if db is None:
            return False
        key = self._cache_key(self._params(t))
        row = db.execute(
            "SELECT raw_json FROM runs WHERE workload = ? "
            "ORDER BY ts DESC, id DESC LIMIT 1", (key,)).fetchone()
        if row is None:
            # Misses are counted only when a lookup actually ran (db
            # configured), so cache_hit_ratio reads hits/lookups.
            self._bump("cache_misses")
            self._m_cache_misses.inc()
            ratio = self._hit_ratio()
            if ratio is not None:
                self._m_hit_ratio.set(ratio)
            return False
        self._first_result(t, json.loads(row[0]))
        self._set_status(t, DONE)
        t.from_cache = True
        t.marks["done"] = time.perf_counter()
        t.times["done"] = time.time()
        self._bump("cache_hits")
        self._m_cache_hits.inc()
        self._m_served.inc()
        ratio = self._hit_ratio()
        if ratio is not None:
            self._m_hit_ratio.set(ratio)
        if "submit" in t.marks:
            self._m_latency.observe(t.marks["done"] - t.marks["submit"])
        self._journal("done", ticket=t.ticket, summary=t.summary,
                      from_cache=True)
        return True

    def _store(self, t: Ticket, row: dict) -> None:
        db = self._open_db()
        if db is None:
            return
        mod = _results_db()
        mod.add_run(db, self._cache_key(self._params(t)), row)

    # ------------------------------------------------------------ serving

    def tickets(self) -> Dict[int, Ticket]:
        return dict(self._tickets)

    def open_tickets(self) -> List[Ticket]:
        return [t for t in self._tickets.values()
                if t.status not in TERMINAL]

    def drain(self) -> Dict[int, Ticket]:
        """One full serving pass: resume preempted buckets, serve
        cache hits, run every queued bucket (with retry / bisection /
        quarantine).  Tickets still RUNNING afterwards were preempted
        this pass and have a checkpoint on disk — drain again (or
        serve()) to continue them."""
        seen = len(self._first_latencies)
        for rec in list(self._resumable):
            self._resume_bucket(rec)
        for t in sorted(self._tickets.values(), key=lambda t: t.ticket):
            if t.status == QUEUED:
                self._serve_cached(t)
        queued = [t for t in sorted(self._tickets.values(),
                                    key=lambda t: t.ticket)
                  if t.status == QUEUED]
        buckets: Dict[tuple, List[Ticket]] = {}
        order: List[tuple] = []
        for t in queued:
            sig = structural_signature(self._params(t))
            if sig not in buckets:
                buckets[sig] = []
                order.append(sig)
            buckets[sig].append(t)
        for sig in order:
            self._run_bucket(buckets[sig])
        fresh = self._first_latencies[seen:]
        if fresh:
            self._m_drain_p50.set(float(np.percentile(fresh, 50)))
            self._m_drain_p99.set(float(np.percentile(fresh, 99)))
        self.write_metrics()
        return self.tickets()

    def serve(self) -> Dict[int, Ticket]:
        """drain() until every ticket is terminal.  Each pass makes at
        least one window of progress per preempted bucket (the budget
        check sits after the dispatch), so this terminates."""
        while True:
            self.drain()
            if not self.open_tickets():
                return self.tickets()

    # ----------------------------------------------------- bucket running

    def _padded(self, items: List[Ticket]) -> List[SimParams]:
        variants = [self._params(t) for t in items]
        vpad = _ceil_pow2(len(variants))
        return variants + [variants[-1]] * (vpad - len(variants))

    def _mark_running(self, items: List[Ticket]) -> None:
        fresh = [t.ticket for t in items if t.status != RUNNING]
        now, wall = time.perf_counter(), time.time()
        for t in items:
            if t.status != RUNNING:
                self._set_status(t, RUNNING)
                t.marks.setdefault("running", now)
                t.times.setdefault("running", wall)
        if fresh:
            self._journal("running", tickets=fresh)

    def _run_bucket(self, items: List[Ticket]) -> None:
        """Run one structural bucket to a terminal or preempted state,
        with bounded retries (exponential backoff) and bisection on
        persistent failure.  Recursion re-pads each half, so padding
        lanes never multiply a quarantine."""
        self._mark_running(items)
        attempt = 0
        while True:
            try:
                sim = SweepSimulator(self._padded(items), self.trace)
                self._execute(items, sim)
                return
            except (DeadlockError, FaultInjected) as e:
                attempt += 1
                if attempt <= self.max_retries:
                    delay = self.backoff_s * (2 ** (attempt - 1))
                    self._bump("retries")
                    self._lg.warning(
                        "bucket %s failed (%s); retry %d/%d in %.3fs",
                        [t.ticket for t in items], e, attempt,
                        self.max_retries, delay)
                    if delay > 0:
                        self._sleep(delay)
                    continue
                if len(items) > 1:
                    mid = len(items) // 2
                    self._bump("bisections")
                    self._lg.warning(
                        "bucket %s still failing after %d retries; "
                        "bisecting", [t.ticket for t in items],
                        self.max_retries)
                    self._run_bucket(items[:mid])
                    self._run_bucket(items[mid:])
                    return
                self._terminal_failure(items[0], e)
                return

    def _execute(self, items: List[Ticket], sim: SweepSimulator) -> None:
        before = batchmod.compile_count()

        def lane_done(lane: int, s) -> None:
            # Padding lanes (>= len(items)) replicate the last real
            # variant; retried/resumed lanes may already have streamed.
            if lane >= len(items):
                return
            t = items[lane]
            if (t.summary is not None or "first_result" in t.marks
                    or t.status in TERMINAL):
                return
            self._first_result(t, self._summary_row(s))

        summaries = sim.run(max_steps=self.max_steps,
                            poll_every=self.poll_every,
                            budget_s=self.budget_s,
                            on_lane_done=lane_done)
        self.compiles_observed += batchmod.compile_count() - before
        self._bump("buckets_run")
        if sim.preempted:
            self._preempt(items, sim)
            return
        for t, s in zip(items, summaries[:len(items)]):
            self._complete(t, self._summary_row(s))

    def _summary_row(self, s) -> dict:
        row = s.to_dict()
        row["kind"] = "service_ticket"
        # Per-tile final clocks ride the record so per-lane bit-identity
        # is checkable from the stored summary alone (the acceptance
        # unit of the kill-and-recover gate).
        row["clock_ps"] = np.asarray(s.clock).astype(
            np.int64).reshape(-1).tolist()
        return row

    def _complete(self, t: Ticket, row: dict) -> None:
        # A streamed lane already observed first_result; if it never
        # streamed (e.g. the whole bucket finished within one poll of a
        # resume), the first availability IS completion.
        if t.summary is None and "first_result" not in t.marks:
            self._first_result(t, row)
        self._set_status(t, DONE)
        # Determinism makes the streamed mid-run summary and the final
        # one bit-identical for a done lane; overwrite keeps the final
        # row authoritative anyway.
        t.summary = row
        t.from_cache = False
        t.marks["done"] = time.perf_counter()
        t.times["done"] = time.time()
        self._m_served.inc()
        if "submit" in t.marks:
            self._m_latency.observe(t.marks["done"] - t.marks["submit"])
        self._journal("done", ticket=t.ticket, summary=row,
                      from_cache=False)
        self._store(t, row)

    def _terminal_failure(self, t: Ticket, e: Exception) -> None:
        err = f"{type(e).__name__}: {e}"
        t.error = err
        t.marks["done"] = time.perf_counter()
        t.times["done"] = time.time()
        if isinstance(e, FaultInjected) and e.transient:
            # Retries exhausted on a TRANSIENT fault: the config is not
            # proven poisonous — mark failed, not quarantined, so an
            # operator resubmits rather than blacklists.
            self._set_status(t, FAILED)
            self._bump("failed")
            self._journal("failed", ticket=t.ticket, error=err)
        else:
            self._set_status(t, QUARANTINED)
            self._bump("quarantined")
            self._journal("quarantined", ticket=t.ticket, error=err)
        self._lg.error("ticket %d (%s) %s: %s", t.ticket, t.label,
                       t.status, err)

    # --------------------------------------------------- preempt / resume

    def _ckpt_path(self, items: List[Ticket]) -> str:
        return os.path.join(self.journal_dir,
                            f"bucket-{items[0].ticket:08d}"
                            f"x{len(items)}.ckpt.npz")

    def _preempt(self, items: List[Ticket], sim: SweepSimulator) -> None:
        path = self._ckpt_path(items)
        sim.save_checkpoint(path)
        rec = {"tickets": [t.ticket for t in items], "checkpoint": path,
               "steps": sim.steps}
        self._journal("preempted", **rec)
        self._drop_resumable(*rec["tickets"])
        self._resumable.append(rec)
        self._bump("preemptions")
        self._lg.info("bucket %s preempted at step %d -> %s",
                      rec["tickets"], sim.steps, path)

    def _resume_bucket(self, rec: dict) -> None:
        items = [self._tickets[tid] for tid in rec["tickets"]
                 if tid in self._tickets]
        if not items or all(t.status in TERMINAL for t in items):
            self._resumable.remove(rec)
            return
        self._mark_running(items)
        try:
            sim = SweepSimulator(self._padded(items), self.trace)
            sim.restore_checkpoint(rec["checkpoint"])
        except (CheckpointCorruptError, ValueError) as e:
            # Torn/corrupt (or mismatched) checkpoint: discard it and
            # fall back to a from-scratch run — the journal stays the
            # source of truth, the checkpoint is only an optimization.
            self._lg.warning("discarding checkpoint %s (%s); re-running "
                             "bucket %s from scratch", rec["checkpoint"],
                             e, rec["tickets"])
            self._bump("checkpoints_discarded")
            self._drop_resumable(*rec["tickets"])
            try:
                os.unlink(rec["checkpoint"])
            except OSError:
                pass
            self._journal("requeued", tickets=rec["tickets"],
                          reason=f"checkpoint corrupt: {e}")
            self._run_bucket(items)
            return
        self._drop_resumable(*rec["tickets"])
        try:
            self._execute(items, sim)
        except (DeadlockError, FaultInjected) as e:
            self._lg.warning("resumed bucket %s failed (%s); re-running "
                             "from scratch", rec["tickets"], e)
            self._run_bucket(items)
            return
        finally:
            # The consumed checkpoint is garbage once the bucket either
            # completed or re-checkpointed under a new path/record.
            if not any(r["checkpoint"] == rec["checkpoint"]
                       for r in self._resumable):
                try:
                    os.unlink(rec["checkpoint"])
                except OSError:
                    pass

    # ------------------------------------------------- metrics / results

    def write_metrics(self) -> Optional[str]:
        """Atomically write the Prometheus exposition to
        ``metrics_path`` (no-op when unset); called after every drain
        so a scraper never sees a half-served pass."""
        if not self.metrics_path:
            return None
        write_exposition(self.metrics_path)
        return self.metrics_path

    def latency_stats(self) -> dict:
        """Serving-latency summary from THIS process's observations
        (plain Python — independent of whether the registry is
        enabled): p50/p99 submit-to-first-result seconds plus the
        lifetime cache-hit ratio.  The numbers bench.py publishes."""
        lat = self._first_latencies

        def pct(q: float) -> Optional[float]:
            return float(np.percentile(lat, q)) if lat else None

        return {
            "first_results": len(lat),
            "p50_first_result_s": pct(50),
            "p99_first_result_s": pct(99),
            "cache_hit_ratio": self._hit_ratio(),
        }

    def result_rows(self) -> Dict[str, dict]:
        """{label: summary row} for every DONE ticket (labels collide
        only when one design point was submitted twice; later tickets
        win, which is also the fresher summary)."""
        out = {}
        for t in sorted(self._tickets.values(), key=lambda t: t.ticket):
            if t.status == DONE and t.summary is not None:
                out[t.label] = t.summary
        return out
