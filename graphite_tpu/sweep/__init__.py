"""Design-space sweep engine: V config variants of one trace as a single
vmapped device program.

Graphite's whole purpose is architecture design-space exploration (Miller
et al., HPCA 2010): the same workload under dozens of latency/bandwidth/
frequency points.  Serially that costs V XLA compiles and V engine runs;
here the VARIANT numeric leaves of ``SimParams`` ride the engine as
batched operands (engine/vparams.py) so one compiled program serves the
whole batch, V variants advance per device dispatch, and each variant's
results are bit-identical to its solo run.

  * ``space``   — STRUCTURAL/VARIANT leaf partition + sweep-spec parsing
  * ``batch``   — variant stacking, the vmapped megarun, result fan-out
  * ``driver``  — request queue bucketing submissions by structural
                  signature, pow2 padding, compile-cache accounting
  * ``service`` — the fault-tolerant layer over all of it: crash-safe
                  ticket journal, bucket bisection around poisoned
                  lanes, preempt/checkpoint/resume, results_db
                  serve-from-cache (ISSUE 15)
"""

from graphite_tpu.sweep.batch import SweepSimulator, run_sweep  # noqa: F401
from graphite_tpu.sweep.driver import SweepDriver  # noqa: F401
from graphite_tpu.sweep.service import SweepService, Ticket  # noqa: F401
from graphite_tpu.sweep.space import (  # noqa: F401
    STRUCTURAL_LEAVES, VARIANT_LEAVES, build_variants, iter_leaves,
    parse_sweep_spec, structural_signature, variant_signature)
