"""Batched multi-variant execution: V ``SimParams`` variants of one trace
through ONE vmapped ``megarun`` program.

Mechanics:

  * Per-variant init states (``make_state`` — DVFS periods and the first
    quantum boundary are the state-borne variant leaves) and per-variant
    ``VariantParams`` operand pytrees are stacked leaf-wise into
    [V]-leading batches.
  * ``sweep_megarun`` vmaps the engine's ``megarun_loop`` over (state,
    operands) with the trace broadcast.  The loop body is masked on each
    lane's ``all_done`` (engine/quantum.megarun_loop), so the device
    loop runs to the SLOWEST variant while finished lanes stay frozen
    bit-exactly.
  * The jit-static argument is the CANONICAL params (sweep/space.py):
    variant values live only in the batched operands, so one compiled
    program serves every design point of a structural bucket.
  * Results fan back out: each lane slices to an ordinary ``SimState``
    and renders through the ordinary ``SimSummary``.

Bit-identity contract (tests/test_sweep.py, bench.py
``sweep_matches_serial``): lane i of a sweep equals a solo
``Simulator`` run of variant i — final clocks, every counter, every
phase counter — because both paths run the same integer math over the
same values; vmap only adds the batch axis.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from graphite_tpu.engine.quantum import megarun_loop
from graphite_tpu.engine.sim import DeadlockError, SimSummary
from graphite_tpu.engine.state import SimState, TraceArrays, make_state
from graphite_tpu.engine.vparams import variant_params
from graphite_tpu.events.schema import Trace
from graphite_tpu.params import SimParams
from graphite_tpu.sweep.space import (canonical_params, structural_diff,
                                      structural_signature)
from graphite_tpu.testing import faults

# In-process compile accounting: bumped when the batched program is
# TRACED (tracing happens exactly once per jit cache miss — i.e. per
# compile request this process makes), never on cache hits.  The sweep
# driver and the CI smoke gate assert on deltas of this counter: one
# compile per structural bucket shape.
_COMPILE_COUNT = 0


def compile_count() -> int:
    return _COMPILE_COUNT


def _count_trace():
    global _COMPILE_COUNT
    _COMPILE_COUNT += 1


def _sweep_megarun_impl(canon: SimParams, bstate, bvp,
                        trace: TraceArrays, max_quanta):
    from graphite_tpu.parallel.mesh import shard_wrap
    _count_trace()

    def run(bstate, bvp, trace, max_quanta):
        def one(st, vp):
            return megarun_loop(canon, vp, st, trace, max_quanta)

        return jax.vmap(one, in_axes=(0, 0))(bstate, bvp)

    return shard_wrap(canon.tile_shards, run, 4)(
        bstate, bvp, trace, max_quanta)


# State donation is opt-in (GRAPHITE_DONATE_STATE=1) and only without
# sharding — the donation chain races buffer lifetime on the CPU PJRT
# client (engine/quantum.py state_donation_enabled has the full note).
_sweep_donate = partial(jax.jit, static_argnums=0,
                        donate_argnums=1)(_sweep_megarun_impl)
_sweep_nodonate = partial(jax.jit, static_argnums=0)(_sweep_megarun_impl)


def sweep_megarun(canon: SimParams, bstate, bvp, trace: TraceArrays,
                  max_quanta):
    """One device dispatch advancing every variant up to ``max_quanta``
    quanta (or its own completion).  ``canon`` must be the CANONICAL
    params of the bucket (space.canonical_params) so the jit cache keys
    on structure, not on visited design points.

    With ``tpu/tile_shards`` > 1 the two batch axes compose: shard_map
    OUTSIDE, vmap INSIDE (parallel/mesh.shard_wrap wraps the vmapped
    body).  The engine's slicing code is written against unbatched tile
    axes, so vmap lifts it over the [V] lane axis while the mesh axis
    splits tiles — V variants x T/S tiles per device in ONE program.

    With ``tpu/shard_state = resident`` the composition flips inside
    out — shard_map OUTSIDE a vmapped shard-local body, state leaves
    sharded along tiles for the whole run — and the host-driven resident
    sweep driver (engine/resident.sweep_megarun) takes over."""
    if canon.shard_state == "resident":
        from graphite_tpu.engine import resident
        return resident.sweep_megarun(canon, bstate, trace, bvp,
                                      max_quanta)
    from graphite_tpu.engine.quantum import state_donation_enabled
    if canon.tile_shards <= 1 and state_donation_enabled():
        return _sweep_donate(canon, bstate, bvp, trace, max_quanta)
    return _sweep_nodonate(canon, bstate, bvp, trace, max_quanta)


def _stack(trees):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def _lane(btree, i: int):
    return jax.tree_util.tree_map(lambda x: x[i], btree)


def _batched_all_done(bstate) -> np.ndarray:
    return np.asarray(jax.vmap(lambda s: s.all_done())(bstate))


class SweepSimulator:
    """The ``Simulator`` shape, over V variants at once.

    All variants must share one structural signature (checked; the
    driver's bucketing guarantees it for queued submissions) and run the
    SAME trace — that is the sweep contract: one workload, many machine
    timings.
    """

    def __init__(self, variants: List[SimParams], trace: Trace):
        if not variants:
            raise ValueError("sweep needs at least one variant")
        base = variants[0]
        sig = structural_signature(base)
        for p in variants[1:]:
            if structural_signature(p) != sig:
                raise ValueError(
                    "sweep variants differ structurally: "
                    + "; ".join(structural_diff(base, p)[:8]))
        if trace.num_tiles < base.num_tiles:
            raise ValueError(
                f"trace has {trace.num_tiles} streams, params expect "
                f"at least {base.num_tiles}")
        from graphite_tpu.isa import EventOp
        ops = np.asarray(trace.ops)
        has_capi = bool(((ops == int(EventOp.SEND))
                         | (ops == int(EventOp.RECV))).any())
        if has_capi and trace.num_tiles > base.num_tiles:
            raise ValueError(
                "CAPI SEND/RECV with multi-thread-per-core scheduling is "
                "not supported yet (channel state is tile-addressed)")
        self.variants = list(variants)
        self.canon = canonical_params(base)
        self.trace = TraceArrays.from_trace(trace)
        self.bstate = _stack([
            make_state(p, has_capi=has_capi, num_streams=trace.num_tiles)
            for p in variants])
        self.bvp = _stack([variant_params(p) for p in variants])
        self.steps = 0
        self.host_seconds = 0.0
        # Set by run() when a wall-clock budget expired before every
        # lane finished: the batch stopped at a window boundary and the
        # state is checkpointable/resumable bit-identically.
        self.preempted = False
        # {lane: steps} — the window step at which each lane's done flag
        # was first observed by run()'s poll (streaming order evidence).
        self.lane_done_step: Dict[int, int] = {}

    @property
    def num_variants(self) -> int:
        return len(self.variants)

    def run(self, max_steps: Optional[int] = None,
            poll_every: int = 8,
            budget_s: Optional[float] = None,
            on_lane_done=None) -> List[SimSummary]:
        """Run windows until EVERY variant is done (or max_steps); one
        SimSummary per variant, in submission order.

        ``budget_s`` is a wall-clock budget: when it expires the loop
        exits at the next WINDOW BOUNDARY with ``self.preempted`` True
        and the batched state intact — save_checkpoint + a later
        restore_checkpoint + run() continues bit-identically (the
        megarun quantum budget is relative to the entry state, and the
        engine is deterministic quantum-by-quantum, so where the
        windows are cut cannot change any lane's math).

        ``on_lane_done(lane, summary)`` streams per-lane results: it
        fires at the first poll that finds lane ``lane`` done — possibly
        many windows before the slowest lane finishes — with that lane's
        FINAL SimSummary (a done lane's state is frozen bit-exactly by
        the masked loop, so the summary streamed early equals the one
        summaries() returns at the end, except host_seconds, which reads
        the wall clock at delivery).  Callback exceptions propagate (the
        lane poll is host code); keep handlers cheap — the batch stalls
        while they run.  ``lane_done_step`` records, per lane, the
        window step count at which its done flag was first observed."""
        from graphite_tpu.log import get_logger
        from graphite_tpu.obs import span
        lg = get_logger("sweep")
        base = self.variants[0]
        lg.info("sweep: %d variants x %d tiles, %d events/tile",
                self.num_variants, base.num_tiles, self.trace.num_events)
        if faults.armed():
            faults.maybe_raise_poison(self.variants)
        self.preempted = False
        self.lane_done_step: Dict[int, int] = {}
        t0 = time.perf_counter()
        qps = base.quanta_per_step
        last_progress = None
        first_dispatch = True
        quanta_v = np.zeros(self.num_variants, dtype=np.int64)
        streamed = np.zeros(self.num_variants, dtype=bool)
        while True:
            window = poll_every if max_steps is None \
                else max(min(poll_every, max_steps - self.steps), 0)
            if window == 0:
                break
            with span("sweep.compile+window" if first_dispatch
                      else "sweep.window",
                      quanta=window * qps, variants=self.num_variants):
                self.bstate = sweep_megarun(
                    self.canon, self.bstate, self.bvp, self.trace,
                    window * qps)
                done_v = _batched_all_done(self.bstate)
                cursor_sum, clock_sum, quanta_v = jax.device_get(
                    (self.bstate.cursor.sum(), self.bstate.clock.sum(),
                     self.bstate.ctr_quantum))
            first_dispatch = False
            if faults.armed():
                faults.fire("raise_in_bucket")
                faults.fire("sigkill_in_bucket")
            # The device loop runs to the slowest variant; window
            # accounting follows that lane.
            self.steps = -(-int(np.max(quanta_v)) // qps)
            newly_done = np.nonzero(done_v & ~streamed)[0]
            for lane in newly_done:
                self.lane_done_step[int(lane)] = self.steps
                if on_lane_done is not None:
                    on_lane_done(int(lane), SimSummary(
                        self.variants[int(lane)],
                        _lane(self.bstate, int(lane)),
                        time.perf_counter() - t0, self.steps))
            streamed |= done_v
            if bool(done_v.all()):
                break
            if max_steps is not None and self.steps >= max_steps:
                break
            if (budget_s is not None
                    and time.perf_counter() - t0 >= budget_s) \
                    or faults.check("exhaust_budget"):
                self.preempted = True
                break
            progress = (int(cursor_sum), int(clock_sum))
            if progress == last_progress:
                raise DeadlockError(
                    f"no progress after {self.steps} steps "
                    + self._stuck_report(done_v, quanta_v))
            last_progress = progress
        self.host_seconds = time.perf_counter() - t0
        lg.info("sweep finished: %d variants, quanta %s, %.2f host-s",
                self.num_variants, np.asarray(quanta_v).tolist(),
                self.host_seconds)
        return self.summaries()

    def _stuck_report(self, done_v, quanta_v) -> str:
        """Per-lane cursor/clock snapshots for the stuck-lane error: a
        wedged serve must be diagnosable from the journal's recorded
        error string alone, without re-running the bucket."""
        cursor = np.asarray(jax.device_get(self.bstate.cursor))
        clock = np.asarray(jax.device_get(self.bstate.clock))
        cursor_v = cursor.reshape(self.num_variants, -1)
        clock_v = clock.reshape(self.num_variants, -1)
        stuck = [i for i, d in enumerate(done_v) if not d]
        lanes = [
            f"lane {i}: cursor_sum={int(cursor_v[i].sum())} "
            f"cursor=[{int(cursor_v[i].min())}..{int(cursor_v[i].max())}] "
            f"clock_ps=[{int(clock_v[i].min())}..{int(clock_v[i].max())}] "
            f"quanta={int(quanta_v[i])}"
            for i in stuck]
        return f"(undone variants: {stuck}; " + "; ".join(lanes) + ")"

    def summaries(self) -> List[SimSummary]:
        """Fan the batched final state out into V independent summaries.
        ``host_seconds`` is the whole batch's wall clock (the variants
        ran together — per-variant host time is not separable).  Lanes
        slice as device arrays so SimSummary's seat-patching (.at[]) and
        int() coercions behave exactly as on a solo run's state."""
        return [SimSummary(self.variants[i], _lane(self.bstate, i),
                           self.host_seconds, self.steps)
                for i in range(self.num_variants)]

    # ---------------------------------------------- checkpoint/resume
    # (schema v25: the solo flatten+save with the [V] lane axis leading
    # every leaf — the sweep service preempts long buckets through this)

    def save_checkpoint(self, path: str) -> None:
        from graphite_tpu.engine.checkpoint import save_sweep_checkpoint
        save_sweep_checkpoint(path, self.bstate, self.steps)

    def restore_checkpoint(self, path: str) -> None:
        """Restore batched state saved from THIS bucket shape (same
        padded variant list, same trace).  run() then continues from the
        checkpointed window boundary bit-identically."""
        from graphite_tpu.engine.checkpoint import load_sweep_checkpoint
        self.bstate, self.steps = load_sweep_checkpoint(
            path, self.variants, num_streams=self.trace.addr.shape[0])


def run_sweep(variants: List[SimParams], trace: Trace,
              max_steps: Optional[int] = None) -> List[SimSummary]:
    return SweepSimulator(variants, trace).run(max_steps=max_steps)
