"""Design-space partition of ``SimParams`` + sweep-spec parsing.

Every ``SimParams`` leaf is either

  * **STRUCTURAL** — shape- or program-bearing: tile counts, cache and
    directory geometry, model selections, engine loop caps (block_events
    K, miss-chain depth, rounds per quantum), queue-model history
    lengths.  All variants batched into one vmapped program must agree
    on every structural leaf — they determine array shapes and the
    compiled program itself.
  * **VARIANT** — numeric scalars that only flow into timing math:
    core/cache/NoC/DRAM latencies and bandwidths, quantum lengths, DVFS
    frequencies, syscall costs.  These enter the engine as traced
    operands (engine/vparams.py) and may differ per batch lane.

The partition is DECLARED here and enforced two ways: the completeness
test (tests/test_sweep.py) walks every numeric leaf and fails when a new
``SimParams`` field is unclassified — a new leaf cannot silently default
into the batch and break vmap safety — and ``structural_signature``
refuses to bucket variants whose structural leaves differ.

Notes on individual calls:

  * ``core.static_costs`` is STRUCTURAL even though it is a latency
    table: the costs are baked into the TRACE at annotation time
    (events/schema.py, tools/annotate_trace.py), and the trace is
    broadcast across the batch — varying them per lane would require
    per-lane traces, not per-lane operands.
  * ``dram.basic_ma_window`` is STRUCTURAL: it is the moving-average
    HISTORY LENGTH of the basic queue model (an effective sample-count
    knob, like the DRAM ring capacity), and its zero/non-zero state
    selects compiled code paths (queue_models.basic_ring).
  * ``max_frequency_ghz`` and ``dvfs_domains`` are VARIANT but
    state-borne rather than operand-borne: they set the initial
    ``period_ps`` arrays in make_state, which the sweep batches per
    lane like the rest of ``SimState``.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, Iterator, List, Optional, Tuple

from graphite_tpu.config import Config, ConfigError
from graphite_tpu.params import SimParams

# ------------------------------------------------------------- partition

VARIANT_LEAVES = frozenset({
    # quantum cadence + DVFS points
    "quantum_ps", "thread_switch_quantum_ps", "max_frequency_ghz",
    # fast-forward accuracy budget (run-ahead ps; the MODE is structural)
    "fast_forward_span_ps",
    "dvfs_domains", "dvfs_sync_delay_cycles",
    # syscall service table
    "syscall_cost_cycles",
    # core
    "core.bp_mispredict_penalty",
    # cache hit/tag latencies
    "l1i.data_access_cycles", "l1i.tags_access_cycles",
    "l1d.data_access_cycles", "l1d.tags_access_cycles",
    "l2.data_access_cycles", "l2.tags_access_cycles",
    # directory
    "directory.access_cycles", "directory.limitless_trap_cycles",
    "directory.inv_ack_cycles",
    # DRAM
    "dram.latency_ns", "dram.per_controller_bandwidth_gbps",
    # NoCs (both logical networks)
    "net_user.flit_width_bits", "net_user.router_delay_cycles",
    "net_user.link_delay_cycles",
    "net_memory.flit_width_bits", "net_memory.router_delay_cycles",
    "net_memory.link_delay_cycles",
    # ATAC delays (absent leaves are simply never visited)
    "net_user.atac.unicast_distance_threshold",
    "net_user.atac.send_hub_router_delay",
    "net_user.atac.receive_hub_router_delay",
    "net_user.atac.star_net_router_delay",
    "net_user.atac.optical_link_delay_cycles",
    "net_memory.atac.unicast_distance_threshold",
    "net_memory.atac.send_hub_router_delay",
    "net_memory.atac.receive_hub_router_delay",
    "net_memory.atac.star_net_router_delay",
    "net_memory.atac.optical_link_delay_cycles",
})

_CACHE_STRUCT = ("line_size", "size_kb", "associativity", "num_banks")
_ATAC_STRUCT = ("num_tiles", "enet_width", "enet_height", "cluster_size",
                "num_clusters", "numx_clusters", "numy_clusters",
                "cluster_width", "cluster_height", "num_access_points")

STRUCTURAL_LEAVES = frozenset({
    "num_tiles", "mesh_width", "mesh_height", "max_threads_per_core",
    "core.static_costs",          # trace-baked (see module docstring)
    "core.bp_size", "core.load_queue_entries", "core.store_queue_entries",
    "l2_max_hw_sharers",
    "directory.total_entries", "directory.associativity",
    "directory.max_hw_sharers",
    "dram.num_controllers", "dram.controller_home_stride",
    "dram.basic_ma_window",       # EMA history length (see docstring)
    "stack_base", "stack_size_per_core", "technology_node",
    "stat_interval_ps", "max_stat_samples",
    "block_events", "max_events_per_quantum", "directory_conflict_rounds",
    "rounds_per_quantum", "quanta_per_step", "max_inv_fanout_per_round",
    "miss_chain", "max_resolve_rounds", "channel_depth",
    "tile_shards",                # selects the sharded vs solo program
    "shard_state",                # replicated vs resident program family
    "route_capacity",             # sizes the resident routing buffers
    "fast_forward",               # compiles the analytic leg in or out
    "segment_events",             # streaming ingest capacity — sizes the
    #   resident segment arrays (a SHAPE), so it can never ride a
    #   vmapped variant axis
} | {f"{c}.{f}" for c in ("l1i", "l1d", "l2") for f in _CACHE_STRUCT}
  | {f"{n}.atac.{f}" for n in ("net_user", "net_memory")
     for f in _ATAC_STRUCT})


def iter_leaves(obj, prefix: str = "") -> Iterator[Tuple[str, object]]:
    """Walk a (possibly nested) params dataclass into (dotted-path, value)
    leaves.  Tuples are ONE leaf (their elements share a classification);
    ``None`` sub-models (e.g. ``atac`` on an electrical mesh) are skipped
    — their leaves simply do not exist for that config."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            yield from iter_leaves(getattr(obj, f.name),
                                   prefix + f.name + ".")
    elif obj is None:
        return
    else:
        yield prefix[:-1], obj


def _tuple_types(value) -> set:
    out = set()
    for v in value:
        if isinstance(v, tuple):
            out |= _tuple_types(v)
        else:
            out.add(type(v))
    return out


def is_numeric_leaf(value) -> bool:
    """Numeric leaves need an explicit STRUCTURAL/VARIANT call; strings
    and booleans are model selections — structural by nature."""
    if isinstance(value, bool):
        return False
    if isinstance(value, (int, float)):
        return True
    if isinstance(value, tuple):
        return any(t in (int, float) for t in _tuple_types(value))
    return False


def classify(path: str, value) -> str:
    """'variant' | 'structural' for one leaf; raises on an unclassified
    numeric leaf (the vmap-safety tripwire for new SimParams fields)."""
    if path in VARIANT_LEAVES:
        return "variant"
    if path in STRUCTURAL_LEAVES or not is_numeric_leaf(value):
        return "structural"
    raise ConfigError(
        f"SimParams leaf {path!r} is numeric but declared neither "
        f"STRUCTURAL nor VARIANT in graphite_tpu/sweep/space.py — new "
        f"leaves must be classified before they can ride (or be barred "
        f"from) a vmapped sweep batch")


def structural_signature(params: SimParams) -> tuple:
    """Hashable signature of every non-VARIANT leaf: two configs batch
    into one sweep bucket iff their signatures are equal."""
    return tuple(sorted(
        (path, repr(value)) for path, value in iter_leaves(params)
        if classify(path, value) != "variant"))


def variant_signature(params: SimParams) -> tuple:
    """Hashable signature of every VARIANT leaf — the other half of the
    partition.  (structural_signature, variant_signature, trace content
    hash) is the durable identity of one design point: the sweep
    service's results_db cache key, stable across processes and
    restarts."""
    return tuple(sorted(
        (path, repr(value)) for path, value in iter_leaves(params)
        if classify(path, value) == "variant"))


def structural_diff(a: SimParams, b: SimParams) -> List[str]:
    """Human-readable list of structural leaves where ``a`` and ``b``
    disagree (empty = batchable together)."""
    da = dict(structural_signature(a))
    db = dict(structural_signature(b))
    out = []
    for path in sorted(set(da) | set(db)):
        if da.get(path) != db.get(path):
            out.append(f"{path}: {da.get(path)} != {db.get(path)}")
    return out


# ------------------------------------------------- canonical static arg

def canonical_params(params: SimParams) -> SimParams:
    """``params`` with every operand-borne VARIANT leaf pinned to a fixed
    value — the jit-STATIC argument of the sweep engine's compiled
    program.  Two buckets with equal structural signatures then hash to
    ONE jit cache key regardless of which variant values they carry (the
    traced code reads those only through the batched ``VariantParams``
    operands), so the compile cache is bounded by bucket SHAPES, not by
    visited design points.  It also acts as a tripwire: an engine read of
    a variant leaf that bypasses ``VariantParams`` would price every
    sweep lane with these canonical constants and fail the
    sweep-vs-serial bit-identity gate (tests/test_sweep.py)."""
    r = dataclasses.replace

    def cache(c):
        return r(c, data_access_cycles=1, tags_access_cycles=1)

    def net(n):
        atac = None
        if n.atac is not None:
            atac = r(n.atac, unicast_distance_threshold=1,
                     send_hub_router_delay=1, receive_hub_router_delay=1,
                     star_net_router_delay=1, optical_link_delay_cycles=1)
        return r(n, flit_width_bits=64, router_delay_cycles=1,
                 link_delay_cycles=1, atac=atac)

    return r(
        params,
        quantum_ps=1_000_000,
        thread_switch_quantum_ps=10_000_000,
        fast_forward_span_ps=0,
        max_frequency_ghz=1.0,
        dvfs_domains=((1.0, ()),),
        dvfs_sync_delay_cycles=1,
        syscall_cost_cycles=(1,) * len(params.syscall_cost_cycles),
        core=r(params.core, bp_mispredict_penalty=1),
        l1i=cache(params.l1i), l1d=cache(params.l1d), l2=cache(params.l2),
        directory=r(params.directory, access_cycles=1,
                    limitless_trap_cycles=1, inv_ack_cycles=1),
        dram=r(params.dram, latency_ns=1.0,
               per_controller_bandwidth_gbps=1.0),
        net_user=net(params.net_user),
        net_memory=net(params.net_memory),
    )


# --------------------------------------------------- sweep-spec parsing

def parse_sweep_spec(specs: List[str]) -> List[Dict[str, str]]:
    """Declarative sweep grammar -> per-variant config-override dicts.

    Each spec string is one AXIS:

      * ``key=v1,v2,...``                    — the axis takes each value
      * ``key1=a1,a2;key2=b1,b2``            — ';'-joined keys ZIP (the
        axis takes (a1, b1) then (a2, b2); lengths must match)

    The variant list is the CROSS PRODUCT of the axes, in spec order
    (later axes vary fastest).  Keys are config paths (``section/key``,
    the same grammar as ``--set``); a key may appear on only one axis.

        parse_sweep_spec(["dram/latency=80,120",
                          "l2_cache/T1/data_access_time=6,8"])
        -> [{latency: 80, dat: 6}, {latency: 80, dat: 8},
            {latency: 120, dat: 6}, {latency: 120, dat: 8}]
    """
    axes: List[List[Dict[str, str]]] = []
    seen_keys: set = set()
    for spec in specs:
        keyvals: List[Tuple[str, List[str]]] = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            key, sep, raw = part.partition("=")
            key = key.strip()
            if not sep or "/" not in key:
                raise ConfigError(
                    f"bad sweep spec {part!r}: expected section/key=v1,v2,...")
            values = [v.strip() for v in raw.split(",")]
            if not values or any(not v for v in values):
                raise ConfigError(f"bad sweep values in {part!r}")
            if key in seen_keys:
                raise ConfigError(
                    f"sweep key {key!r} appears on more than one axis")
            seen_keys.add(key)
            keyvals.append((key, values))
        if not keyvals:
            raise ConfigError(f"empty sweep spec {spec!r}")
        n = len(keyvals[0][1])
        for key, values in keyvals[1:]:
            if len(values) != n:
                raise ConfigError(
                    f"zipped sweep axis {spec!r}: {key!r} has "
                    f"{len(values)} values, expected {n}")
        axes.append([{k: v[i] for k, v in keyvals} for i in range(n)])
    variants: List[Dict[str, str]] = []
    for combo in itertools.product(*axes):
        merged: Dict[str, str] = {}
        for d in combo:
            merged.update(d)
        variants.append(merged)
    return variants


def variant_label(overrides: Dict[str, str]) -> str:
    """Short stable label for one variant's override point.  Key names
    shorten to their last path component unless two swept keys share it
    (l1/l2 data_access_time), which would collapse distinct axes into
    one label — those keep the full path."""
    if not overrides:
        return "base"
    tails = [k.rsplit("/", 1)[-1] for k in overrides]
    dup = {t for t in tails if tails.count(t) > 1}
    def short(k):
        t = k.rsplit("/", 1)[-1]
        return k if t in dup else t
    return ",".join(f"{short(k)}={v}" for k, v in sorted(overrides.items()))


def build_variants(cfg: Config, specs: List[str],
                   num_tiles: Optional[int] = None
                   ) -> List[Tuple[str, Dict[str, str], SimParams]]:
    """Sweep specs -> [(label, overrides, SimParams)], validated: every
    variant must share the base config's STRUCTURAL signature (a swept
    structural key — a cache size, a tile count — fails loudly with the
    differing leaves, instead of silently compiling per point)."""
    points = parse_sweep_spec(specs)
    out = []
    base_sig = None
    for overrides in points:
        c = cfg.copy()
        for k, v in overrides.items():
            c.set(k, v)
        p = SimParams.from_config(c, num_tiles=num_tiles)
        sig = structural_signature(p)
        if base_sig is None:
            base_sig = sig
        elif sig != base_sig:
            diff = structural_diff(out[0][2], p)
            raise ConfigError(
                "sweep crosses a STRUCTURAL boundary — these keys change "
                "shapes or the compiled program and cannot vary within "
                "one vmapped batch (split into separate sweeps): "
                + "; ".join(diff[:8]))
        out.append((variant_label(overrides), overrides, p))
    return out
