"""Persistent XLA compile-cache policy for the ENTRY POINTS (cli.py,
bench.py, the weak-scaling legs).

The package import (graphite_tpu/__init__.py) already points jax at
``<repo>/.jax_cache`` when running from a checkout — the right default
for tests and development, where the cache should live and die with the
tree.  The launchers add a user-level policy on top, because a CLI
invocation may run from an INSTALLED package (no checkout, so no cache
at all) and megarun programs cost minutes of XLA compile time per
(params, shapes) key:

  * ``$GRAPHITE_COMPILE_CACHE`` set to a path — use exactly that.
  * set but EMPTY — disable persistent caching for this process.
  * unset — keep whatever the import chose (checkout cache); if the
    import chose nothing, fall back to ``~/.cache/graphite_tpu/xla``.

Call :func:`enable_compile_cache` before the first jit dispatch; it is
idempotent and never raises for an unwritable directory (jax degrades
to in-memory caching on cache I/O errors).
"""

from __future__ import annotations

import os

DEFAULT_CACHE = os.path.join("~", ".cache", "graphite_tpu", "xla")
ENV_VAR = "GRAPHITE_COMPILE_CACHE"


def resolve_cache_dir(env: dict | None = None) -> str | None:
    """The directory the policy selects, or None to disable.  Split from
    the jax.config mutation so tests can check the policy pure."""
    env = os.environ if env is None else env
    raw = env.get(ENV_VAR)
    if raw is not None:
        return os.path.expanduser(raw) if raw.strip() else None
    import jax
    current = jax.config.jax_compilation_cache_dir
    if current:
        return current
    return os.path.expanduser(DEFAULT_CACHE)


def enable_compile_cache() -> str | None:
    """Apply the policy; returns the active cache dir (None = disabled)."""
    import jax

    target = resolve_cache_dir()
    if target is None:
        jax.config.update("jax_compilation_cache_dir", None)
        return None
    os.makedirs(target, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", target)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return target
