"""Binary trace IO — the native frontend's wire format.

``native/`` (libcarbon_trace) captures a real pthreads application's
events into this format (header + per-tile record arrays); this module
loads it into a ``Trace``, performing the two frontend duties the C++
side leaves to the host:

  * **address compaction** — native pointers are 47-bit host VAs, beyond
    the engine's 2^37 address budget (int32 line ids); pages are remapped
    to dense ids preserving intra-page locality (set indexing and line
    adjacency within a page survive; cross-page adjacency of a sparse
    host heap carries no simulation meaning),
  * **cache-line splitting** — one MEM event per touched line, arg2=1 on
    continuations (the reference splits in Core::initiateMemoryAccess,
    core.cc:173-245).

Format (little-endian):
    8 bytes   magic "GTPUTRC1"
    u32       num_tiles
    per tile: u32 count, then count x { i32 op, pad32, i64 addr, i32 arg,
              i32 arg2 }  (the C struct layout of native/src Event)
"""

from __future__ import annotations

import struct

import numpy as np

from graphite_tpu.events.schema import Trace
from graphite_tpu.isa import EventOp

MAGIC = b"GTPUTRC1"
PAGE_BITS = 12
_REC = np.dtype([("op", "<i4"), ("_pad", "<i4"), ("addr", "<i8"),
                 ("arg", "<i4"), ("arg2", "<i4")])

_MEM_OPS = (int(EventOp.MEM_READ), int(EventOp.MEM_WRITE),
            int(EventOp.ATOMIC))


def load_binary_trace(path: str, line_size: int = 64) -> Trace:
    with open(path, "rb") as f:
        if f.read(8) != MAGIC:
            raise ValueError(f"{path}: not a graphite_tpu binary trace")
        (num_tiles,) = struct.unpack("<I", f.read(4))
        per_tile = []
        for _ in range(num_tiles):
            (n,) = struct.unpack("<I", f.read(4))
            per_tile.append(np.frombuffer(f.read(n * _REC.itemsize),
                                          dtype=_REC))

    # ---- address compaction over every page TOUCHED by any access (not
    # just start pages — a straddling access must not spill into an
    # unrelated host page's compacted id).  COMPUTE/BRANCH i-fetch
    # addresses (real code addresses under the TSan frontend) compact
    # through the same map — code and data pages never collide, so their
    # L1I behavior survives the remap.
    page_sz = 1 << PAGE_BITS
    touched = set()
    mem_masks = [np.isin(r["op"], _MEM_OPS) for r in per_tile]
    ifetch_ops = (int(EventOp.COMPUTE), int(EventOp.BRANCH))
    for rec, m in zip(per_tile, mem_masks):
        for a, sz in zip(rec["addr"][m], rec["arg"][m]):
            a, sz = int(a), max(1, int(sz))
            touched.update(range(a >> PAGE_BITS,
                                 ((a + sz - 1) >> PAGE_BITS) + 1))
        fm = np.isin(rec["op"], ifetch_ops)
        fa = rec["addr"][fm].astype(np.int64)
        span = np.maximum(rec["arg2"][fm].astype(np.int64), 1) * 4
        start = fa >> PAGE_BITS
        end = (fa + span - 1) >> PAGE_BITS       # ~4 B per instruction
        touched.update(np.unique(start).tolist())
        for a, b in zip(start[start != end], end[start != end]):
            touched.update(range(int(a), int(b) + 1))
    page_map = {p: i for i, p in enumerate(sorted(touched))}

    # ---- page-bounded splitting, per-piece remap, line splitting
    events = [[] for _ in range(num_tiles)]
    for t, rec in enumerate(per_tile):
        out = events[t]
        for op, a, arg, arg2 in zip(rec["op"], rec["addr"], rec["arg"],
                                    rec["arg2"]):
            op, a, arg, arg2 = int(op), int(a), int(arg), int(arg2)
            if op in _MEM_OPS:
                end = a + max(1, arg)
                first = True
                while a < end:
                    ca = (page_map[a >> PAGE_BITS] << PAGE_BITS) \
                        | (a & (page_sz - 1))
                    nxt = min((a // line_size + 1) * line_size,
                              (a // page_sz + 1) * page_sz, end)
                    out.append((op, ca, nxt - a, 0 if first else 1))
                    a = nxt
                    first = False
            elif op in ifetch_ops and (a >> PAGE_BITS) in page_map:
                ca = (page_map[a >> PAGE_BITS] << PAGE_BITS) \
                    | (a & (page_sz - 1))
                out.append((op, ca, arg, arg2))
            else:
                out.append((op, a, arg, arg2))
        if not out or out[-1][0] != int(EventOp.DONE):
            out.append((int(EventOp.DONE), 0, 0, 0))

    n = max(len(e) for e in events)
    ops = np.zeros((num_tiles, n), dtype=np.int32)
    addr = np.zeros((num_tiles, n), dtype=np.int64)
    arg = np.zeros((num_tiles, n), dtype=np.int32)
    arg2 = np.zeros((num_tiles, n), dtype=np.int32)
    for t, evs in enumerate(events):
        if not evs:
            continue
        a = np.asarray(evs, dtype=np.int64)
        k = len(evs)
        ops[t, :k] = a[:, 0]
        addr[t, :k] = a[:, 1]
        arg[t, :k] = a[:, 2]
        arg2[t, :k] = a[:, 3]
    return Trace(ops=ops, addr=addr, arg=arg, arg2=arg2)
