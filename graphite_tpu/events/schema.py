"""Per-tile event-stream schema — the frontend/engine contract.

This is the TPU build's analog of the reference's Pin analysis-call feed:
one record per *modeled step* of a tile, covering the union of what the
reference's instrumentation delivers to the timing models —
instruction decode + queue (reference: pin/instruction_modeling.cc:350-410),
lite-mode memory modeling (reference: pin/lite/memory_modeling.cc:13-57),
user messaging / sync / spawn dynamic instructions (reference:
common/tile/core/instruction.h:166-200), and thread lifecycle.

Layout: structure-of-arrays, fixed shape ``[num_tiles, num_events]``,
padded with NOP so every tile's stream has the same length (static shapes
for XLA).  Field meaning depends on the opcode (see ``EventOp``):

===============  =====================  ==============  =======================
op               addr (int64)           arg (int32)     arg2 (int32)
===============  =====================  ==============  =======================
NOP              -                      -               -
COMPUTE          block start pc         cost (cycles)   instruction count
MEM_READ         byte address           size (bytes)    0 / 1 = line-split cont.
MEM_WRITE        byte address           size (bytes)    0 / 1 = line-split cont.
ATOMIC           byte address           size (bytes)    0 / 1 = line-split cont.
BRANCH           pc                     taken (0/1)     0
SEND             -                      size (bytes)    destination tile
RECV             -                      size (bytes)    source tile
BARRIER_WAIT     -                      barrier id      participant count
MUTEX_LOCK       -                      mutex id        0
MUTEX_UNLOCK     -                      mutex id        0
COND_WAIT        -                      cond id         mutex id (held)
COND_SIGNAL      -                      cond id         0
COND_BROADCAST   -                      cond id         0
JOIN             -                      -               child stream
THREAD_START     -                      -               -
YIELD            -                      -               -
SYNC             wake time (ps)         cost (cycles)   0
SPAWN            -                      cost (cycles)   child tile
STALL            until time (ps)        -               0
DVFS_SET         -                      module id       frequency (MHz)
DONE             -                      -               -
===============  =====================  ==============  =======================

Conventions the frontend must uphold (mirroring reference behavior):
  * Memory accesses are split at cache-line boundaries by the *frontend*
    (the reference splits them in Core::initiateMemoryAccess,
    common/tile/core/core.cc:173-245); the engine models one line per
    MEM_* event.
  * COMPUTE collapses a run of non-memory, non-branch instructions into an
    aggregate (cost, icount) pair; cost is the sum of the static per-type
    costs the reference reads from [core/static_instruction_costs]
    (carbon_sim.cfg:189-200).  The engine models instruction fetch for the
    block from `addr` assuming a mean 4-byte encoding.
  * Streams end with one DONE; slots after it are NOP padding.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from graphite_tpu.isa import EventOp, InstructionType

__all__ = ["Trace", "TraceBuilder", "EventOp"]

# Mean instruction encoding length assumed when modeling i-fetch for a
# COMPUTE block (x86 averages ~3.7 bytes; the reference fetches each
# instruction's true bytes via Pin, which a trace no longer carries).
ICACHE_BYTES_PER_INSTRUCTION = 4


@dataclasses.dataclass
class Trace:
    """A complete per-tile event-stream bundle (numpy; device placement is
    the engine's job)."""

    ops: np.ndarray    # [T, N] int32 (EventOp)
    addr: np.ndarray   # [T, N] int64
    arg: np.ndarray    # [T, N] int32
    arg2: np.ndarray   # [T, N] int32

    @property
    def num_tiles(self) -> int:
        return self.ops.shape[0]

    @property
    def num_events(self) -> int:
        return self.ops.shape[1]

    def __post_init__(self):
        shape = self.ops.shape
        for name in ("addr", "arg", "arg2"):
            a = getattr(self, name)
            if a.shape != shape:
                raise ValueError(f"trace field {name} shape {a.shape} != {shape}")
        self.ops = self.ops.astype(np.int32, copy=False)
        self.addr = self.addr.astype(np.int64, copy=False)
        self.arg = self.arg.astype(np.int32, copy=False)
        self.arg2 = self.arg2.astype(np.int32, copy=False)

    # -------------------------------------------------------------- io

    def save(self, path: str) -> None:
        np.savez_compressed(
            path, ops=self.ops, addr=self.addr, arg=self.arg, arg2=self.arg2
        )

    @classmethod
    def load(cls, path: str) -> "Trace":
        with np.load(path) as z:
            return cls(ops=z["ops"], addr=z["addr"], arg=z["arg"], arg2=z["arg2"])

    # ------------------------------------------------------------ utility

    def content_hash(self) -> str:
        """sha256 over the event arrays (values + shapes) — the trace's
        durable identity.  Two traces with equal hashes produce
        bit-identical simulations under equal params, so this keys the
        sweep service's serve-from-cache tier (and matches the disk
        trace cache's content-addressing philosophy)."""
        import hashlib
        h = hashlib.sha256()
        for a in (self.ops, self.addr, self.arg, self.arg2):
            h.update(str(a.shape).encode())
            h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()

    def instruction_count(self) -> int:
        """Total modeled instructions across all tiles (for MIPS math).
        Line-split continuation events (arg2=1 on MEM_*) belong to the
        same instruction as their predecessor and are not re-counted."""
        ops = self.ops
        n = int(np.sum(np.where(ops == EventOp.COMPUTE, self.arg2, 0)))
        mem = np.isin(ops, (EventOp.MEM_READ, EventOp.MEM_WRITE, EventOp.ATOMIC))
        n += int(np.sum(mem & (self.arg2 == 0)))
        n += int(np.sum(ops == EventOp.BRANCH))
        return n

    def pad_to(self, num_events: int) -> "Trace":
        if num_events < self.num_events:
            raise ValueError("pad_to cannot shrink a trace")
        if num_events == self.num_events:
            return self
        T, N = self.ops.shape
        pad = num_events - N

        def _pad(a, dtype):
            return np.concatenate(
                [a, np.zeros((T, pad), dtype=dtype)], axis=1)

        return Trace(
            ops=_pad(self.ops, np.int32),
            addr=_pad(self.addr, np.int64),
            arg=_pad(self.arg, np.int32),
            arg2=_pad(self.arg2, np.int32),
        )


class TraceBuilder:
    """Append-style builder for one trace: per-tile event lists packed into
    the dense [T, N] layout (the software analog of the reference's
    per-thread analysis-call sequence)."""

    def __init__(self, num_tiles: int, line_size: int = 64,
                 static_costs: Optional[Dict[InstructionType, int]] = None):
        self.num_tiles = num_tiles
        self.line_size = line_size
        self.static_costs = static_costs or {}
        self._events: List[List[Tuple[int, int, int, int]]] = [
            [] for _ in range(num_tiles)
        ]
        self._done = [False] * num_tiles

    # ----------------------------------------------------------- emitters

    def _emit(self, tile: int, op: EventOp, addr: int = 0, arg: int = 0,
              arg2: int = 0) -> None:
        if self._done[tile]:
            raise ValueError(f"tile {tile} already DONE")
        self._events[tile].append((int(op), int(addr), int(arg), int(arg2)))

    # Register-operand annotations (IOCOOM scoreboard, reference
    # iocoom_core_model.h:82 Scoreboard _register_scoreboard): events may
    # name one source and one destination register out of NUM_REGISTERS
    # architectural registers; ids are packed into arg2's high bits.  The
    # reference tracks 512 Pin register ids; the trace schema compresses
    # to 32 (frontends map ids mod 32 — a collision only adds a false
    # dependency, which is conservative, never optimistic).
    NUM_REGISTERS = 32
    _REG_SRC_SHIFT = 20    # COMPUTE: bits 20-24 = src reg + 1
    _REG_DST_SHIFT = 25    # COMPUTE: bits 25-29 = dst reg + 1
    _MEM_DST_SHIFT = 8     # MEM_READ: bits 8-12 = dest reg + 1

    def compute(self, tile: int, cost_cycles: int, icount: int,
                pc: int = 0x400000, src_reg: Optional[int] = None,
                dst_reg: Optional[int] = None) -> None:
        assert icount < (1 << self._REG_SRC_SHIFT)
        arg2 = icount
        if src_reg is not None:
            assert 0 <= src_reg < self.NUM_REGISTERS
            arg2 |= (src_reg + 1) << self._REG_SRC_SHIFT
        if dst_reg is not None:
            assert 0 <= dst_reg < self.NUM_REGISTERS
            arg2 |= (dst_reg + 1) << self._REG_DST_SHIFT
        self._emit(tile, EventOp.COMPUTE, pc, cost_cycles, arg2)

    def instructions(self, tile: int, types: Sequence[InstructionType],
                     pc: int = 0x400000) -> None:
        """Convenience: collapse a typed instruction run via the builder's
        static-cost table (what a real frontend does at decode)."""
        cost = sum(self.static_costs[t] for t in types)
        self.compute(tile, cost, len(types), pc)

    def _mem(self, tile: int, op: EventOp, addr: int, size: int,
             dest_reg: Optional[int] = None) -> None:
        # Line-splitting happens here, as in the reference's core entry
        # (core.cc:173-245): one event per touched line.  Continuation
        # events of a straddling access carry arg2 bit 0 = 1 so
        # instruction counting attributes the whole access to one
        # instruction.  ``dest_reg`` (loads) rides arg2 bits 8-12 on the
        # first line's event — the scoreboard destination.
        end = addr + max(1, size)
        line = self.line_size
        a = addr
        first = True
        dbits = 0
        if dest_reg is not None:
            assert 0 <= dest_reg < self.NUM_REGISTERS
            dbits = (dest_reg + 1) << self._MEM_DST_SHIFT
        while a < end:
            line_end = (a // line + 1) * line
            chunk = min(end, line_end) - a
            self._emit(tile, op, a, chunk,
                       (0 | dbits) if first else 1)
            a += chunk
            first = False

    def read(self, tile: int, addr: int, size: int = 8,
             dest_reg: Optional[int] = None) -> None:
        self._mem(tile, EventOp.MEM_READ, addr, size, dest_reg=dest_reg)

    def write(self, tile: int, addr: int, size: int = 8) -> None:
        self._mem(tile, EventOp.MEM_WRITE, addr, size)

    def atomic(self, tile: int, addr: int, size: int = 8) -> None:
        self._mem(tile, EventOp.ATOMIC, addr, size)

    def branch(self, tile: int, taken: bool, pc: int = 0x400000) -> None:
        self._emit(tile, EventOp.BRANCH, pc, int(taken), 0)

    def send(self, tile: int, dst: int, size: int = 8) -> None:
        self._emit(tile, EventOp.SEND, 0, size, dst)

    def recv(self, tile: int, src: int, size: int = 8) -> None:
        self._emit(tile, EventOp.RECV, 0, size, src)

    def syscall(self, tile: int, syscall_class, nbytes: int = 0,
                vm_arg: int = 0) -> None:
        """Marshalled system call served by the MCP's syscall server
        (reference: syscall_model.cc -> syscall_server.cc:43-130);
        ``nbytes`` = marshalled argument/result payload.  ``vm_arg``
        carries the VMManager payload in the addr field (mmap/munmap:
        length; brk: the requested data-segment size, i.e. the delta
        over the initial break — vm_manager.cc, engine/vm.py)."""
        self._emit(tile, EventOp.SYSCALL, vm_arg, int(syscall_class),
                   nbytes)

    def barrier(self, tile: int, barrier_id: int, participants: int) -> None:
        self._emit(tile, EventOp.BARRIER_WAIT, 0, barrier_id, participants)

    def mutex_lock(self, tile: int, mutex_id: int) -> None:
        self._emit(tile, EventOp.MUTEX_LOCK, 0, mutex_id, 0)

    def mutex_unlock(self, tile: int, mutex_id: int) -> None:
        self._emit(tile, EventOp.MUTEX_UNLOCK, 0, mutex_id, 0)

    def cond_wait(self, tile: int, cond_id: int, mutex_id: int) -> None:
        """Release ``mutex_id`` (which the tile must hold), park until a
        signal, then re-acquire it before continuing."""
        self._emit(tile, EventOp.COND_WAIT, 0, cond_id, mutex_id)

    def cond_signal(self, tile: int, cond_id: int) -> None:
        self._emit(tile, EventOp.COND_SIGNAL, 0, cond_id, 0)

    def cond_broadcast(self, tile: int, cond_id: int) -> None:
        self._emit(tile, EventOp.COND_BROADCAST, 0, cond_id, 0)

    def spawn(self, tile: int, child: int, cost_cycles: int = 0) -> None:
        """Start ``child``'s stream (which must begin with THREAD_START)."""
        self._emit(tile, EventOp.SPAWN, 0, cost_cycles, child)

    def join(self, tile: int, child: int) -> None:
        """Block until ``child``'s stream reaches DONE."""
        self._emit(tile, EventOp.JOIN, 0, 0, child)

    def thread_start(self, tile: int) -> None:
        """Gate this tile's stream on being SPAWNed by another tile."""
        self._emit(tile, EventOp.THREAD_START, 0, 0, 0)

    def thread_yield(self, tile: int) -> None:
        """Give up the core so the scheduler can seat the next queued
        stream (CarbonThreadYield; only meaningful when the trace has
        more streams than tiles)."""
        self._emit(tile, EventOp.YIELD, 0, 0, 0)

    def enable_models(self, tile: int) -> None:
        """Region-of-interest start (CarbonEnableModels): timing + counters
        resume globally."""
        self._emit(tile, EventOp.ENABLE_MODELS, 0, 0, 0)

    def disable_models(self, tile: int) -> None:
        """Region-of-interest end (CarbonDisableModels): compute/memory
        events fast-forward at zero cost, uncounted, until re-enabled."""
        self._emit(tile, EventOp.DISABLE_MODELS, 0, 0, 0)

    def stall_until(self, tile: int, time_ps: int) -> None:
        self._emit(tile, EventOp.STALL, time_ps, 0, 0)

    def dvfs_set(self, tile: int, module: int, freq_ghz: float) -> None:
        self._emit(tile, EventOp.DVFS_SET, 0, module, int(round(freq_ghz * 1000)))

    def done(self, tile: int) -> None:
        self._emit(tile, EventOp.DONE)
        self._done[tile] = True

    # ------------------------------------------------------------- finish

    def build(self, min_events: Optional[int] = None) -> Trace:
        for t in range(self.num_tiles):
            if not self._done[t]:
                self.done(t)
        n = max(len(ev) for ev in self._events)
        if min_events is not None:
            n = max(n, min_events)
        T = self.num_tiles
        ops = np.zeros((T, n), dtype=np.int32)
        addr = np.zeros((T, n), dtype=np.int64)
        arg = np.zeros((T, n), dtype=np.int32)
        arg2 = np.zeros((T, n), dtype=np.int32)
        for t, evs in enumerate(self._events):
            if not evs:
                continue
            rec = np.asarray(evs, dtype=np.int64)
            k = len(evs)
            ops[t, :k] = rec[:, 0]
            addr[t, :k] = rec[:, 1]
            arg[t, :k] = rec[:, 2]
            arg2[t, :k] = rec[:, 3]
        return Trace(ops=ops, addr=addr, arg=arg, arg2=arg2)
