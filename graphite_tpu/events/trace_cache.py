"""On-disk trace cache — skip synthesis/annotation on warm runs.

BENCH_r05 died at rc=124 with ``parsed: null``: the captured rows'
trace synthesis + static-decode annotation (~890k events per capture)
re-ran from scratch every invocation and ate the driver budget, and the
annotator's progress lines were the last thing on stdout when the
driver killed the process.  Generated AND annotated traces are
deterministic functions of (generator, arguments, schema), so they
cache as npz files keyed by a content hash:

    $GRAPHITE_TRACE_CACHE   cache directory; '' disables caching
                            (default ~/.cache/graphite_tpu/traces)

``cached(key_parts, builder)`` returns the cached Trace when the key
hits, else runs ``builder()`` and stores the result.  Corrupt or
unreadable cache entries fall through to the builder (a cache must
never be able to sink a run); writes go through a temp file + rename so
a killed run can't leave a truncated entry behind.
"""

from __future__ import annotations

import hashlib
import os
import sys
import tempfile
from typing import Callable, Sequence

# Bump to invalidate every cached trace (event-schema or generator
# semantics changes).
CACHE_VERSION = 1


def cache_dir() -> str:
    """Resolved cache directory ('' = caching disabled)."""
    d = os.environ.get("GRAPHITE_TRACE_CACHE")
    if d is None:
        d = os.path.join(os.path.expanduser("~"), ".cache",
                         "graphite_tpu", "traces")
    return d


def file_digest(paths: Sequence) -> str:
    """sha256 over the CONTENT of ``paths`` (in order) — cache keys must
    change when the code that generates the trace changes, not only when
    its arguments do (an edited generator silently served the pre-edit
    trace otherwise).  Missing/unreadable files hash as their name, so a
    key can still form (the builder will fail loudly on its own)."""
    h = hashlib.sha256()
    for p in paths:
        h.update(b"\x00")
        try:
            with open(p, "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(str(p).encode())
    return h.hexdigest()


def cache_key(key_parts: Sequence, src_files: Sequence = ()) -> str:
    """Stable content hash of the generator identity + arguments + the
    generating code's file contents."""
    h = hashlib.sha256()
    h.update(f"v{CACHE_VERSION}".encode())
    for part in key_parts:
        h.update(b"\x00")
        h.update(repr(part).encode())
    if src_files:
        h.update(file_digest(src_files).encode())
    return h.hexdigest()[:32]


def _schema_file() -> str:
    from graphite_tpu.events import schema
    return schema.__file__


def cached(key_parts: Sequence, builder: Callable[[], "Trace"],
           src_files: Sequence = ()):
    """Return the Trace for ``key_parts``, from cache when possible.

    ``src_files``: files whose CONTENT the built trace depends on (the
    generator module, vendored benchmark sources, the capture
    toolchain); the event schema module is always included."""
    from graphite_tpu.events.schema import Trace

    d = cache_dir()
    if not d:
        return builder()
    path = os.path.join(
        d, cache_key(key_parts,
                     list(src_files) + [_schema_file()]) + ".npz")
    if os.path.exists(path):
        try:
            return Trace.load(path)
        except Exception as e:   # corrupt entry: rebuild, best-effort drop
            print(f"trace_cache: unreadable entry {path}: {e}",
                  file=sys.stderr)
            try:
                os.unlink(path)
            except OSError:
                pass
    trace = builder()
    tmp = None
    try:
        os.makedirs(d, exist_ok=True)
        # Suffix must stay ".npz" — np.savez appends it otherwise.
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
        os.close(fd)
        trace.save(tmp)
        os.replace(tmp, path)
        tmp = None
    except Exception as e:       # full disk, read-only home, ...
        print(f"trace_cache: write failed for {path}: {e}",
              file=sys.stderr)
    finally:
        if tmp is not None:      # failed save must not orphan its tmp
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return trace
