"""Synthetic trace generators — the first event source.

These play the role of the reference's synthetic test applications
(reference: tests/benchmarks/synthetic_memory/synthetic_memory.cc,
tests/benchmarks/synthetic_network/) and of the unit tests' hand-driven
access sequences (reference: tests/unit/shared_mem_basic/shared_mem_basic.cc:16-44):
deterministic per-tile event streams with controlled compute/memory mixes
and sharing patterns, used for golden-timing tests and benchmarking before
a live (Pin-equivalent) frontend exists.

Address-space convention: each tile's private heap lives at
``PRIVATE_BASE + tile * PRIVATE_SPAN``; shared regions live under
``SHARED_BASE``.  Addresses are synthetic — the engine only hashes them
(timing-only simulation, like the reference's lite mode).
"""

from __future__ import annotations

import numpy as np

from graphite_tpu.events.schema import (
    ICACHE_BYTES_PER_INSTRUCTION, Trace, TraceBuilder)
from graphite_tpu.isa import EventOp

PRIVATE_BASE = 0x1000_0000
PRIVATE_SPAN = 0x0100_0000
SHARED_BASE = 0x8000_0000


def gen_compute(num_tiles: int, blocks: int = 100, cost_cycles: int = 50,
                icount_per_block: int = 50) -> Trace:
    """Pure-compute streams: golden total time = blocks * cost (+ i-fetch)."""
    tb = TraceBuilder(num_tiles)
    for t in range(num_tiles):
        pc = 0x400000
        for _ in range(blocks):
            tb.compute(t, cost_cycles, icount_per_block, pc=pc)
            pc += icount_per_block * ICACHE_BYTES_PER_INSTRUCTION
    return tb.build()


def gen_private_mem(num_tiles: int, accesses: int = 1000,
                    working_set_kb: int = 16, read_fraction: float = 0.7,
                    compute_cycles: int = 5, seed: int = 0,
                    line_size: int = 64) -> Trace:
    """Uniform-random accesses within each tile's private working set.

    With working_set <= L1D size this is an all-hit stream; larger working
    sets sweep the L1/L2/DRAM hit-rate curve — the same knob the reference's
    synthetic_memory benchmark exposes.
    """
    rng = np.random.default_rng(seed)
    tb = TraceBuilder(num_tiles, line_size=line_size)
    span = working_set_kb * 1024
    for t in range(num_tiles):
        base = PRIVATE_BASE + t * PRIVATE_SPAN
        offsets = (rng.integers(0, span // 8, size=accesses) * 8)
        reads = rng.random(accesses) < read_fraction
        for i in range(accesses):
            if compute_cycles:
                tb.compute(t, compute_cycles, compute_cycles)
            a = int(base + offsets[i])
            if reads[i]:
                tb.read(t, a, 8)
            else:
                tb.write(t, a, 8)
    return tb.build()


def gen_stream(num_tiles: int, lines: int = 2048, passes: int = 1,
               write: bool = False, line_size: int = 64) -> Trace:
    """Sequential streaming over a private buffer (DRAM-bandwidth shaped)."""
    tb = TraceBuilder(num_tiles, line_size=line_size)
    for t in range(num_tiles):
        base = PRIVATE_BASE + t * PRIVATE_SPAN
        for _ in range(passes):
            for i in range(lines):
                a = base + i * line_size
                if write:
                    tb.write(t, a, 8)
                else:
                    tb.read(t, a, 8)
    return tb.build()


def gen_shared_readers(num_tiles: int, lines: int = 64, passes: int = 4,
                       line_size: int = 64) -> Trace:
    """All tiles read the same shared region: exercises S-state sharing
    (every line ends with all tiles in the sharer bitmap)."""
    tb = TraceBuilder(num_tiles, line_size=line_size)
    for t in range(num_tiles):
        for _ in range(passes):
            for i in range(lines):
                tb.read(t, SHARED_BASE + i * line_size, 8)
    return tb.build()


def gen_migratory(num_tiles: int, lines: int = 16, rounds: int = 8,
                  line_size: int = 64) -> Trace:
    """Migratory sharing: tiles take turns read-modify-writing shared lines
    (exercises M->flush->M ping-pong, the reference's shared_mem_test
    pattern, tests/unit/shared_mem_test*/)."""
    tb = TraceBuilder(num_tiles, line_size=line_size)
    for r in range(rounds):
        for t in range(num_tiles):
            for i in range(lines):
                a = SHARED_BASE + i * line_size
                tb.read(t, a, 8)
                tb.write(t, a, 8)
            tb.compute(t, 20, 20)
    return tb.build()


def gen_ping_pong(num_tiles: int, messages: int = 32,
                  size: int = 64) -> Trace:
    """CAPI ping-pong between tile pairs (reference: tests/apps/ping_pong)."""
    if num_tiles % 2:
        raise ValueError("ping_pong needs an even tile count")
    tb = TraceBuilder(num_tiles)
    for a in range(0, num_tiles, 2):
        b = a + 1
        for _ in range(messages):
            tb.send(a, b, size)
            tb.recv(b, a, size)
            tb.send(b, a, size)
            tb.recv(a, b, size)
    return tb.build()


def gen_barrier_compute(num_tiles: int, phases: int = 8,
                        max_cost: int = 400, seed: int = 0) -> Trace:
    """Unbalanced compute phases separated by global barriers (exercises the
    sync server path, reference: common/system/sync_server.h SimBarrier)."""
    rng = np.random.default_rng(seed)
    tb = TraceBuilder(num_tiles)
    for p in range(phases):
        costs = rng.integers(max_cost // 4, max_cost, size=num_tiles)
        for t in range(num_tiles):
            tb.compute(t, int(costs[t]), int(costs[t]))
            tb.barrier(t, 0, num_tiles)
    return tb.build()


def gen_threads_oversubscribed(num_streams: int, compute_blocks: int = 8,
                               cost_cycles: int = 100,
                               yields: int = 2) -> Trace:
    """More app threads than tiles — the ThreadScheduler workload
    (reference: every PARSEC config runs 64 threads on fewer cores,
    tests/Makefile.parsec:8-26; scheduling per thread_scheduler.h:30-56).

    Streams split in halves: parents (first half) spawn one child each,
    compute with private-memory traffic, join the child, and finish;
    children gate on THREAD_START, compute with explicit YIELDs (so
    rotation exercises both the voluntary and preemptive paths), and
    finish.  Run it with ``general/total_cores < num_streams`` and
    ``max_threads_per_core >= 2``.
    """
    assert num_streams % 2 == 0
    half = num_streams // 2
    tb = TraceBuilder(num_streams)
    for s in range(half):
        child = half + s
        tb.compute(s, cost_cycles, cost_cycles)
        tb.spawn(s, child, cost_cycles=10)
        base = PRIVATE_BASE + s * PRIVATE_SPAN
        for b in range(compute_blocks):
            tb.compute(s, cost_cycles, cost_cycles)
            tb.read(s, base + (b * 64) % 4096)
        tb.join(s, child)
        tb.done(s)
    for s in range(half, num_streams):
        tb.thread_start(s)
        base = PRIVATE_BASE + s * PRIVATE_SPAN
        for b in range(compute_blocks):
            tb.compute(s, cost_cycles, cost_cycles)
            tb.write(s, base + (b * 64) % 4096)
            if yields and b % max(compute_blocks // yields, 1) == 0:
                tb.thread_yield(s)
        tb.done(s)
    return tb.build()


def gen_lock_contention(num_tiles: int, acquisitions: int = 16,
                        critical_cycles: int = 50) -> Trace:
    """All tiles repeatedly take one mutex (reference: tests/unit/many_mutex)."""
    tb = TraceBuilder(num_tiles)
    for k in range(acquisitions):
        for t in range(num_tiles):
            tb.mutex_lock(t, 0)
            tb.compute(t, critical_cycles, critical_cycles)
            tb.mutex_unlock(t, 0)
    return tb.build()


def gen_radix(num_tiles: int, keys_per_tile: int = 4096, radix: int = 256,
              seed: int = 0, line_size: int = 64,
              max_events_per_tile: int | None = None) -> Trace:
    """Address-accurate SPLASH-2 radix-sort trace (reference:
    tests/benchmarks/radix/radix.C vendored from SPLASH-2).

    Reproduces the memory behavior of one digit-pass of the parallel radix
    sort: (1) local histogram of each tile's keys (sequential key reads +
    scattered count increments), (2) barrier, (3) parallel prefix over the
    per-tile histograms (reads of other tiles' shared count arrays),
    (4) barrier, (5) permutation writes of keys to their globally-ranked
    positions (scattered writes into the shared output array).  Compute
    events between accesses model the ~10 arithmetic ops per key of the
    original loop bodies.
    """
    rng = np.random.default_rng(seed)
    tb = TraceBuilder(num_tiles, line_size=line_size)
    n_total = keys_per_tile * num_tiles
    keys = rng.integers(0, radix, size=(num_tiles, keys_per_tile))

    key_array = PRIVATE_BASE           # per-tile key input (private span)
    hist_array = SHARED_BASE           # [num_tiles, radix] shared histograms
    out_array = SHARED_BASE + 0x400_0000  # shared sorted output

    # Global ranks for the permutation phase (computed once, host side).
    flat = keys.reshape(-1)
    order = np.argsort(flat, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(n_total)
    rank = rank.reshape(num_tiles, keys_per_tile)

    for t in range(num_tiles):
        base = key_array + t * PRIVATE_SPAN
        # Phase 1: histogram — read key, bump count.
        for i in range(keys_per_tile):
            tb.compute(t, 4, 4)
            tb.read(t, base + i * 8, 8)
            d = int(keys[t, i])
            tb.write(t, hist_array + (t * radix + d) * 8, 8)
        tb.barrier(t, 0, num_tiles)
        # Phase 3: binary-tree parallel prefix over the per-tile
        # histograms (the reference's prefix_tree of 2P nodes,
        # radix.C:79,507-575: each processor merges its pair's densities
        # up the tree and reads rank offsets back down) — O(radix log P)
        # work per tile, NOT O(radix x P): the all-pairs version this
        # replaces made the 1024-tile trace 16x denser than the
        # algorithm it models.
        stride = max(1, line_size // 8)
        tree_array = SHARED_BASE + 0x200_0000   # [2P, radix] tree nodes
        levels = max(1, (num_tiles - 1).bit_length())
        node_base = 0
        width = num_tiles
        for lvl in range(levels):
            pair = t >> (lvl + 1)
            # ONE representative tile per pair merges (the pair's lowest
            # tile): read both child nodes, write the parent.  The
            # reference lets the later arrival merge; which sibling does
            # it is timing detail — the modeled traffic is one merge per
            # pair per level, O(T) total merges.
            if t % (1 << (lvl + 1)) == 0 and width > 1:
                sib = node_base + (t >> lvl) + 1
                parent = node_base + width + pair
                for d in range(0, radix, stride):
                    tb.compute(t, 2, 2)
                    tb.read(t, tree_array + (sib * radix + d) * 8, 8)
                    tb.write(t, tree_array + (parent * radix + d) * 8, 8)
            node_base += width
            width = max(1, width // 2)
        tb.barrier(t, 1, num_tiles)
        # Down-sweep: read this tile's rank offsets from its ancestor
        # nodes (log P nodes, one cache line of densities each).
        node_base = 0
        width = num_tiles
        for lvl in range(levels):
            node = node_base + (t >> lvl)
            tb.compute(t, 2, 2)
            tb.read(t, tree_array + (node * radix) * 8, 8)
            node_base += width
            width = max(1, width // 2)
        # Phase 5: permutation — read key, write to ranked slot.
        for i in range(keys_per_tile):
            tb.compute(t, 6, 6)
            tb.read(t, base + i * 8, 8)
            tb.write(t, out_array + int(rank[t, i]) * 8, 8)
        tb.barrier(t, 2, num_tiles)
    trace = tb.build()
    if max_events_per_tile is not None and trace.num_events > max_events_per_tile:
        raise ValueError(
            f"radix trace has {trace.num_events} events/tile > cap")
    return trace


def gen_fft(num_tiles: int, points_per_tile: int = 1024,
            line_size: int = 64, writeback: bool = False) -> Trace:
    """Address-accurate SPLASH-2 FFT trace (reference:
    tests/benchmarks/fft/fft.C — the six-step 1D radix-sqrt(n) FFT).

    Each tile owns ``points_per_tile`` complex points (16 B each) of the
    sqrt(n) x sqrt(n) matrix, laid out in a shared array.  The six-step
    structure is: transpose, local 1D FFTs, transpose, local FFTs,
    transpose — the transposes are the all-to-all: each tile reads a
    block from EVERY other tile's partition and writes into its own,
    which is the communication signature FFT stresses at 256 tiles
    (BASELINE config 2).

    ``writeback=True`` alternates the transpose DIRECTION (src -> dst,
    then dst -> src, ...), as fft.C's ping-ponging x/trans arrays do:
    each transpose then WRITES lines the previous one left read-shared
    across up to line_size/16 tiles, so the trace carries the EX-on-
    multi-sharer invalidation fan-outs of the real kernel.  Default
    False preserves the historical one-directional trace bit-exactly
    (the equality-gate fixtures are pinned to it).
    """
    tb = TraceBuilder(num_tiles, line_size=line_size)
    elem = 16                                  # complex double
    part = points_per_tile * elem              # bytes per tile partition
    src = SHARED_BASE                          # shared matrix
    dst = SHARED_BASE + 0x1000_0000            # transpose target
    # points exchanged with each partner per transpose
    blk = max(1, points_per_tile // max(1, num_tiles))
    log_n = max(1, (points_per_tile * num_tiles).bit_length() - 1)

    def transpose(t, phase, a_from=src, a_to=dst):
        for p in range(num_tiles):
            for i in range(blk):
                a_src = a_from + p * part + (t * blk + i) * elem
                a_dst = a_to + t * part + (p * blk + i) * elem
                tb.compute(t, 2, 2)
                tb.read(t, a_src, elem)
                tb.write(t, a_dst, elem)
        tb.barrier(t, phase, num_tiles)

    def local_fft(t, phase, base=dst):
        # 1D FFTs over the tile's own rows: ~5 log2(n) flops per point,
        # sequential read-modify-write sweep.
        for i in range(points_per_tile):
            tb.compute(t, 5 * log_n, 5 * log_n)
            a = base + t * part + i * elem
            tb.read(t, a, elem)
            tb.write(t, a, elem)
        tb.barrier(t, phase, num_tiles)

    for t in range(num_tiles):
        if writeback:
            transpose(t, 0, src, dst)
            local_fft(t, 1, dst)
            transpose(t, 2, dst, src)
            local_fft(t, 3, src)
            transpose(t, 4, src, dst)
        else:
            transpose(t, 0)
            local_fft(t, 1)
            transpose(t, 2)
            local_fft(t, 3)
            transpose(t, 4)
    return tb.build()


def gen_lu(num_tiles: int, matrix_blocks: int = 8, block_lines: int = 4,
           line_size: int = 64) -> Trace:
    """Address-accurate SPLASH-2 LU trace (reference:
    tests/benchmarks/lu/contiguous_blocks/lu.C).

    The B x B block-decomposed factorization: at step k the diagonal
    block's owner factors it; owners of perimeter blocks (row/column k)
    then read the DIAGONAL block and update; owners of interior blocks
    read their two perimeter blocks and update — producer-consumer
    sharing at block granularity, the directory-MSI stress of BASELINE
    config 2.  Blocks are assigned round-robin (2D scatter).
    """
    tb = TraceBuilder(num_tiles, line_size=line_size)
    nb = matrix_blocks
    blk_bytes = block_lines * line_size

    def block_addr(i, j):
        return SHARED_BASE + (i * nb + j) * blk_bytes

    def owner(i, j):
        return (i * nb + j) % num_tiles

    def sweep(t, i, j, reads, writes=True, flops=8):
        """Read the listed source blocks line by line, update own block."""
        for li in range(block_lines):
            for (ri, rj) in reads:
                tb.read(t, block_addr(ri, rj) + li * line_size, 8)
            tb.compute(t, flops * len(reads) + flops, flops)
            if writes:
                tb.write(t, block_addr(i, j) + li * line_size, 8)

    bar = 0
    for k in range(nb):
        # diagonal factorization by its owner
        t = owner(k, k)
        sweep(t, k, k, reads=[(k, k)], flops=12)
        for tt in range(num_tiles):
            tb.barrier(tt, bar % 16, num_tiles)
        bar += 1
        # perimeter updates read the diagonal block
        for j in range(k + 1, nb):
            sweep(owner(k, j), k, j, reads=[(k, k)])
            sweep(owner(j, k), j, k, reads=[(k, k)])
        for tt in range(num_tiles):
            tb.barrier(tt, bar % 16, num_tiles)
        bar += 1
        # interior updates read their row/column perimeter blocks
        for i in range(k + 1, nb):
            for j in range(k + 1, nb):
                sweep(owner(i, j), i, j, reads=[(i, k), (k, j)])
        for tt in range(num_tiles):
            tb.barrier(tt, bar % 16, num_tiles)
        bar += 1
    return tb.build()


def gen_barnes(num_tiles: int, bodies_per_tile: int = 64,
               interactions_per_body: int = 16, iterations: int = 2,
               hot_cells: int = 32, seed: int = 0,
               line_size: int = 64) -> Trace:
    """Address-accurate SPLASH-2 Barnes-Hut trace (reference:
    tests/benchmarks/barnes/).

    Per iteration: (1) tree build — every tile writes its bodies' cell
    links into the shared tree region (scattered shared writes);
    (2) force computation — for each body, walk the tree: reads of the
    HOT top-level cells (read by all tiles — wide sharing) mixed with
    random deeper body records (sparse sharing); (3) position update —
    private writes.  Captures the irregular read-mostly sharing that
    makes barnes a directory stress.
    """
    rng = np.random.default_rng(seed)
    tb = TraceBuilder(num_tiles, line_size=line_size)
    body_bytes = 64                          # one body record = one line
    tree = SHARED_BASE                       # shared cell array
    bodies = SHARED_BASE + 0x1000_0000       # shared body array
    n_bodies = num_tiles * bodies_per_tile

    for it in range(iterations):
        for t in range(num_tiles):
            # (1) tree build: insert own bodies (scattered shared writes)
            for i in range(bodies_per_tile):
                cell = int(rng.integers(0, hot_cells * 8))
                tb.compute(t, 10, 10)
                tb.write(t, tree + cell * body_bytes, 8)
            tb.barrier(t, (3 * it) % 16, num_tiles)
            # (2) force computation: hot-cell reads + random body reads
            for i in range(bodies_per_tile):
                for k in range(interactions_per_body):
                    if k % 4 == 0:      # top-of-tree cell, read by all
                        cell = int(rng.integers(0, hot_cells))
                        tb.read(t, tree + cell * body_bytes, 8)
                    else:               # random remote body
                        b = int(rng.integers(0, n_bodies))
                        tb.read(t, bodies + b * body_bytes, 8)
                    tb.compute(t, 12, 12)
            tb.barrier(t, (3 * it + 1) % 16, num_tiles)
            # (3) update own bodies
            for i in range(bodies_per_tile):
                own = t * bodies_per_tile + i
                tb.compute(t, 8, 8)
                tb.write(t, bodies + own * body_bytes, 8)
            tb.barrier(t, (3 * it + 2) % 16, num_tiles)
    return tb.build()


GENERATORS = {
    "compute": gen_compute,
    "private_mem": gen_private_mem,
    "stream": gen_stream,
    "shared_readers": gen_shared_readers,
    "migratory": gen_migratory,
    "ping_pong": gen_ping_pong,
    "barrier_compute": gen_barrier_compute,
    "lock_contention": gen_lock_contention,
    "radix": gen_radix,
    "fft": gen_fft,
    "lu": gen_lu,
    "barnes": gen_barnes,
}
