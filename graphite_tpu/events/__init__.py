"""Event streams: the frontend <-> timing-engine contract (see schema.py)."""

from graphite_tpu.events.schema import Trace, TraceBuilder  # noqa: F401
