"""Segment planning for streaming trace ingest (engine/ingest.py).

The whole-trace program uploads every event column to the device at
startup, so trace length is bounded by HBM and capture-then-simulate is a
two-epoch workflow.  Streaming mode chunks the [T, N] event arrays into
fixed-capacity SEGMENTS of ``segment_events`` columns and keeps exactly
two resident per run (active + prefetch); this module is the host side of
that split — per-row segment slicing, base-offset capping, and the
per-segment content digests the sweep service keys streamed tickets on.

Coordinates: engine reads stay GLOBAL (event index into the full [*, N]
stream); a resident segment covers per-row columns [base[r], base[r]+C)
and the rebase happens at the gather (TraceArrays.local_cols).  Bases are
always capped at ``max(N - C, 0)`` so the trace-end clamp (reads at
min(pos, N-1)) always lands on a REAL resident column — segment values
are then bit-identical to whole-trace values at every readable index, by
construction.
"""

from __future__ import annotations

import hashlib
from typing import List, Tuple

import numpy as np

from graphite_tpu.events.schema import Trace

__all__ = ["SegmentPlan", "plan_seams", "streamed_content_hash",
           "segment_digests"]


def plan_seams(n_total: int, segment_events: int) -> List[Tuple[int, int]]:
    """Uniform [start, end) segment spans of the full stream — the
    nominal seam schedule (actual swaps are per-row and cursor-driven;
    this is the reporting/digest granularity)."""
    if segment_events <= 0:
        return [(0, n_total)]
    out = []
    s = 0
    while s < n_total:
        out.append((s, min(s + segment_events, n_total)))
        s += segment_events
    return out or [(0, 0)]


def segment_digests(trace: Trace, segment_events: int) -> List[str]:
    """sha256 per uniform segment (ops/addr/arg/arg2 column spans, values
    + shapes) — the content-addressed identity of each ingest chunk, so
    a capture still being annotated can hash segments as they land
    (events/trace_cache.py's philosophy, per chunk)."""
    digests = []
    for s, e in plan_seams(trace.num_events, segment_events):
        h = hashlib.sha256()
        for a in (trace.ops, trace.addr, trace.arg, trace.arg2):
            chunk = np.ascontiguousarray(a[:, s:e])
            h.update(str(chunk.shape).encode())
            h.update(chunk.tobytes())
        digests.append(h.hexdigest())
    return digests


def streamed_content_hash(trace: Trace, segment_events: int) -> str:
    """Durable identity of a STREAMED submission: the chained hash of its
    per-segment digests (+ the segmentation itself).  Two submissions
    with equal streamed hashes simulate bit-identically under equal
    params — streamed execution is bit-identical to whole-trace (the
    ingest contract), and equal per-segment digests mean equal content —
    so this keys the sweep service's serve-from-cache tier for streamed
    traces the way Trace.content_hash does for whole ones."""
    h = hashlib.sha256()
    h.update(f"seg{segment_events}".encode())
    for d in segment_digests(trace, segment_events):
        h.update(b"\x00")
        h.update(d.encode())
    return h.hexdigest()


class SegmentPlan:
    """Host-side segment slicer over one Trace.

    Holds the full event arrays in engine layout (addr int64 [R, N],
    meta int32 [3, R, N] — stacked ONCE, the same field-leading layout
    TraceArrays.from_trace builds) and cuts [R, C] per-row windows at
    arbitrary base offsets: the active segment at init, hard rebuilds at
    committed cursors, and predicted prefetch windows.
    """

    def __init__(self, trace: Trace, segment_events: int):
        if segment_events <= 0:
            raise ValueError(
                f"segment_events must be >= 1 for streaming: "
                f"{segment_events}")
        addr = np.asarray(trace.addr, dtype=np.int64)
        if addr.max(initial=0) >= (1 << 37):
            raise ValueError(
                "trace addresses must be < 2^37 (int32 line-id layout)")
        self.addr = addr
        self.meta = np.stack([
            np.asarray(trace.ops, dtype=np.int32),
            np.asarray(trace.arg, dtype=np.int32),
            np.asarray(trace.arg2, dtype=np.int32),
        ], axis=0)
        self.num_rows = addr.shape[0]
        self.n_total = addr.shape[1]
        # Resident capacity never exceeds the stream (a segment larger
        # than the trace IS the whole trace, one segment, zero seams).
        self.segment_events = min(segment_events, self.n_total)
        # Highest legal base: keeps column N-1 resident in every tail
        # segment, so the trace-end clamp reads real data (bit-identity
        # with the whole-trace clamp junk).
        self.max_base = max(self.n_total - self.segment_events, 0)
        self.num_segments = len(plan_seams(self.n_total,
                                           self.segment_events))

    def cap_bases(self, bases: np.ndarray) -> np.ndarray:
        return np.clip(np.asarray(bases, dtype=np.int64),
                       0, self.max_base).astype(np.int32)

    def slice_rows(self, bases: np.ndarray):
        """(addr [R, C] int64, meta [3, R, C] int32) holding each row's
        columns [bases[r], bases[r] + C).  Bases must be pre-capped, so
        every column is real data (no padding is ever readable)."""
        C = self.segment_events
        b = np.asarray(bases, dtype=np.int64)
        cols = b[:, None] + np.arange(C, dtype=np.int64)[None, :]
        rows = np.arange(self.num_rows)[:, None]
        addr = self.addr[rows, cols]
        meta = self.meta[:, rows, cols]
        return addr, np.ascontiguousarray(meta)

    def segment_bytes(self) -> int:
        """Device bytes of ONE resident segment (int64 addr + 3x int32
        meta per event per row)."""
        return self.num_rows * self.segment_events * (8 + 3 * 4)
