"""Test-support package: the fault-injection harness (testing.faults).

Distinct from ``graphite_tpu.engine.testing`` (engine-level cache
warmers): this package holds the hooks PRODUCTION code calls so that
tests and the CI recovery gate can make the service layer fail on
demand — nothing here runs unless a fault is armed.
"""

from graphite_tpu.testing import faults  # noqa: F401
from graphite_tpu.testing.faults import FaultInjected  # noqa: F401
