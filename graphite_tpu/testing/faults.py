"""Fault-injection harness for the sweep service's robustness story.

Every recovery claim in ``sweep/service.py`` (ISSUE 15) is exercised, not
asserted: production code calls the hooks below at its fault-relevant
sites, and the hooks do NOTHING unless a fault is armed — either
programmatically (``arm``/``disarm``, for in-process tests) or through
the ``GRAPHITE_FAULTS`` environment variable (inherited by subprocess
legs, which is how the run_tests.sh kill-and-recover gate reaches into a
service process it is about to SIGKILL).

Spec grammar — ``site[:arg]`` terms joined by ``;``::

    GRAPHITE_FAULTS="raise_in_bucket:2"           # raise at the 2nd window
    GRAPHITE_FAULTS="sigkill_in_bucket:2"         # SIGKILL self at the 2nd
    GRAPHITE_FAULTS="truncate_checkpoint"         # corrupt the next save
    GRAPHITE_FAULTS="exhaust_budget:3"            # budget reads empty from
                                                  # the 3rd window check on
    GRAPHITE_FAULTS="poison:dram/latency=120"     # every bucket containing
                                                  # a variant whose
                                                  # dram.latency_ns leaf
                                                  # matches raises

Sites and semantics:

  * ``raise_in_bucket[:N]`` — one-shot TRANSIENT fault: the Nth window
    dispatch of any SweepSimulator raises ``FaultInjected``; later hits
    pass.  Exercises the service's bounded-retry/backoff path.
  * ``sigkill_in_bucket[:N]`` — the process SIGKILLs itself at the Nth
    window boundary: no cleanup, no atexit — the honest crash the
    journal must survive.
  * ``truncate_checkpoint[:N]`` — the Nth checkpoint written after
    arming is truncated to a third of its bytes AFTER the atomic rename,
    modeling torn storage under the writer: loads must surface
    ``CheckpointCorruptError``, and the service must fall back to
    re-running the bucket.
  * ``exhaust_budget[:N]`` — from the Nth budget check on, the wall-clock
    budget reads as exhausted: deterministic preemption without
    wall-clock-sensitive tests.
  * ``poison:<config-path>=<value>`` — a PERSISTENT per-variant fault:
    any bucket holding a variant whose SimParams leaf for that config
    path equals the value raises at dispatch.  Real DeadlockErrors
    cannot be provoked per-LANE (all lanes share one trace), so this is
    the deterministic poison lane the bucket-bisection path needs.

Counters are per-process and reset by ``disarm()``; the env var is
re-read on every check so a parent can arm a child leg purely through
its environment.
"""

from __future__ import annotations

import os
import signal
from typing import Dict, List

__all__ = ["FaultInjected", "arm", "disarm", "armed", "fire", "check",
           "poison_lanes", "maybe_raise_poison", "maybe_truncate"]


class FaultInjected(RuntimeError):
    """An armed fault fired.  ``transient`` marks faults that succeed on
    retry (one-shot raise_in_bucket); persistent faults (poison lanes)
    re-fire every attempt and must be bisected/quarantined instead."""

    def __init__(self, msg: str, site: str = "", transient: bool = False):
        super().__init__(msg)
        self.site = site
        self.transient = transient


# Programmatic arms (tests in-process) layered OVER the env specs
# (subprocess legs); hit counters are shared across both.
_armed: Dict[str, str] = {}
_env_raw = None
_env_specs: Dict[str, str] = {}
_hits: Dict[str, int] = {}


def _parse(raw: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for term in raw.split(";"):
        term = term.strip()
        if not term:
            continue
        site, _, arg = term.partition(":")
        out[site.strip()] = arg.strip()
    return out


def _specs() -> Dict[str, str]:
    global _env_raw, _env_specs
    raw = os.environ.get("GRAPHITE_FAULTS", "")
    if raw != _env_raw:
        _env_raw = raw
        _env_specs = _parse(raw)
    if _armed:
        merged = dict(_env_specs)
        merged.update(_armed)
        return merged
    return _env_specs


def arm(spec: str) -> None:
    """Arm fault(s) in-process (same grammar as GRAPHITE_FAULTS)."""
    _armed.update(_parse(spec))


def disarm() -> None:
    """Drop every programmatic arm and reset all hit counters."""
    _armed.clear()
    _hits.clear()


def armed() -> bool:
    return bool(_specs())


def _nth(arg: str) -> int:
    try:
        return max(int(arg), 1) if arg else 1
    except ValueError:
        return 1


def fire(site: str) -> None:
    """Count one pass through ``site``; on the armed Nth pass, fault."""
    specs = _specs()
    if site not in specs:
        return
    n = _hits.get(site, 0) + 1
    _hits[site] = n
    if n != _nth(specs[site]):
        return
    if site.startswith("sigkill"):
        os.kill(os.getpid(), signal.SIGKILL)
    raise FaultInjected(f"injected fault at {site!r} (hit {n})",
                        site=site, transient=True)


def check(site: str) -> bool:
    """Sticky predicate: True on every pass from the armed Nth on."""
    specs = _specs()
    if site not in specs:
        return False
    n = _hits.get(site, 0) + 1
    _hits[site] = n
    return n >= _nth(specs[site])


def poison_lanes(variants) -> List[bool]:
    """Per-variant flags for the armed ``poison:<path>=<value>`` spec —
    matched against the variant's SimParams leaves (config paths map to
    dotted leaf paths by their last component, e.g. ``dram/latency``
    matches ``dram.latency_ns`` via the numeric value)."""
    from graphite_tpu.sweep.space import iter_leaves
    arg = _specs().get("poison")
    if not arg:
        return [False] * len(variants)
    leaf, _, want = arg.partition("=")
    leaf = leaf.strip().replace("/", ".")
    want = want.strip()
    section, _, tail = leaf.rpartition(".")

    def matches(params) -> bool:
        for path, value in iter_leaves(params):
            if section and not path.startswith(section + "."):
                continue
            if not (path == leaf or path.rsplit(".", 1)[-1]
                    .startswith(tail)):
                continue
            try:
                if float(value) == float(want):
                    return True
            except (TypeError, ValueError):
                if str(value) == want:
                    return True
        return False

    return [matches(p) for p in variants]


def maybe_raise_poison(variants) -> None:
    """Raise a PERSISTENT FaultInjected when any lane matches the armed
    poison spec — called at bucket dispatch, so the whole batch fails
    exactly the way a real poisoned lane sinks its bucket."""
    flags = poison_lanes(variants)
    if any(flags):
        idx = [i for i, f in enumerate(flags) if f]
        raise FaultInjected(
            f"injected poison fault: lanes {idx} match armed spec "
            f"{_specs().get('poison')!r}", site="poison", transient=False)


def maybe_truncate(path: str) -> None:
    """Truncate ``path`` (post-rename) when truncate_checkpoint is armed
    — the torn-storage model the corrupt-load path must survive."""
    specs = _specs()
    if "truncate_checkpoint" not in specs:
        return
    n = _hits.get("truncate_checkpoint", 0) + 1
    _hits["truncate_checkpoint"] = n
    if n != _nth(specs["truncate_checkpoint"]):
        return
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size // 3, 1))
