"""Process-wide metrics registry: counters, gauges, histograms.

obs/spans.py profiles ONE run's host path and obs/metrics.py samples ONE
run's device gauges; a *serving* process (sweep/service.py) needs
process-lifetime aggregates instead — tickets served, cache hits,
latency distributions — rendered in the two formats a fleet scrapes:

  * ``render_exposition`` — Prometheus text exposition (``# HELP`` /
    ``# TYPE`` / ``name{label="v"} value`` lines, histograms as
    ``_bucket{le=...}``/``_sum``/``_count`` families), written
    atomically by the service each drain (``write_exposition``);
  * ``snapshot`` — a JSON-able dict for bench rows and tests.

Disabled-path discipline mirrors spans.py: every mutation
(``inc``/``set``/``observe``) starts with one attribute check on the
owning registry and returns — no allocation, no lock, no clock read —
so instrumentation stays in the serving path unconditionally.  The
registry never touches simulated time: it is host-side bookkeeping
only, and metrics-off runs are bit-identical by construction.

Histograms use FIXED bucket upper bounds chosen at creation (defaults
sized for ticket latencies: 1 ms .. 5 min).  ``percentile`` linearly
interpolates inside the bucket that crosses the rank — the standard
Prometheus ``histogram_quantile`` estimate, hand-checkable in tests.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "get_registry", "enable_metrics", "metrics_enabled",
           "render_exposition", "parse_exposition", "write_exposition",
           "DEFAULT_LATENCY_BUCKETS", "INGEST_STALL_BUCKETS",
           "ingest_metrics"]

# Ticket/first-result latency bucket bounds (seconds).  Serving latencies
# straddle "cache hit" (sub-ms) to "compile + long bucket" (minutes).
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

# Streaming-ingest seam stalls (seconds): a swap served from a completed
# prefetch is sub-ms (one device select); a synchronous hard rebuild of a
# large segment can take whole seconds.
INGEST_STALL_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def _label_key(labelnames: Tuple[str, ...],
               labels: Dict[str, str]) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    """Shared shape: a name, help text, declared label names, and one
    value-cell per observed label combination."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 help_text: str, labelnames: Tuple[str, ...]):
        self._registry = registry
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return _label_key(self.labelnames, labels)


class Counter(_Metric):
    """Monotone float counter (per label set)."""

    kind = "counter"

    def __init__(self, registry, name, help_text, labelnames):
        super().__init__(registry, name, help_text, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._registry.enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up")
        k = self._key(labels)
        self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def samples(self):
        return [(self.name, dict(zip(self.labelnames, k)), v)
                for k, v in sorted(self._values.items())]


class Gauge(_Metric):
    """Set-to-current-value gauge (per label set)."""

    kind = "gauge"

    def __init__(self, registry, name, help_text, labelnames):
        super().__init__(registry, name, help_text, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        if not self._registry.enabled:
            return
        self._values[self._key(labels)] = float(value)

    def add(self, delta: float, **labels) -> None:
        """Delta update (may be negative).  For gauges several writers
        share — e.g. tickets_in_state fed by more than one SweepService
        in one process — absolute set() would make the last writer
        clobber the others; deltas compose."""
        if not self._registry.enabled:
            return
        key = self._key(labels)
        self._values[key] = self._values.get(key, 0.0) + float(delta)

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def samples(self):
        return [(self.name, dict(zip(self.labelnames, k)), v)
                for k, v in sorted(self._values.items())]


class _HistCell:
    __slots__ = ("bucket_counts", "sum", "count")

    def __init__(self, nbuckets: int):
        self.bucket_counts = [0] * (nbuckets + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Fixed-bucket histogram: cumulative-at-render, per-bucket counts
    internally.  ``bounds`` are finite upper bounds in increasing order;
    an implicit +Inf bucket catches the overflow."""

    kind = "histogram"

    def __init__(self, registry, name, help_text, labelnames,
                 bounds: Iterable[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(registry, name, help_text, labelnames)
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError("histogram bounds must strictly increase")
        self._cells: Dict[Tuple[str, ...], _HistCell] = {}

    def _cell(self, labels: Dict[str, str]) -> _HistCell:
        k = self._key(labels)
        cell = self._cells.get(k)
        if cell is None:
            cell = self._cells[k] = _HistCell(len(self.bounds))
        return cell

    def observe(self, value: float, **labels) -> None:
        if not self._registry.enabled:
            return
        value = float(value)
        cell = self._cell(labels)
        # First bucket whose upper bound holds the value; +Inf otherwise.
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if value <= b:
                idx = i
                break
        cell.bucket_counts[idx] += 1
        cell.sum += value
        cell.count += 1

    def count(self, **labels) -> int:
        cell = self._cells.get(self._key(labels))
        return cell.count if cell is not None else 0

    def total(self, **labels) -> float:
        cell = self._cells.get(self._key(labels))
        return cell.sum if cell is not None else 0.0

    def percentile(self, p: float, **labels) -> Optional[float]:
        """Estimate the p-quantile (p in [0, 1]) by linear interpolation
        inside the bucket that crosses rank p*count; None when empty.
        Overflow (+Inf bucket) clamps to the largest finite bound — the
        estimate degrades gracefully instead of inventing a value."""
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"quantile {p} outside [0, 1]")
        cell = self._cells.get(self._key(labels))
        if cell is None or cell.count == 0:
            return None
        target = p * cell.count
        cum = 0.0
        lo = 0.0
        for i, b in enumerate(self.bounds):
            n = cell.bucket_counts[i]
            if n and cum + n >= target:
                frac = (target - cum) / n
                return lo + (b - lo) * max(frac, 0.0)
            cum += n
            lo = b
        return self.bounds[-1] if self.bounds else None

    def samples(self):
        """Exposition-shaped samples: cumulative ``_bucket`` rows per
        ``le`` bound (+Inf last), then ``_sum`` and ``_count``."""
        out = []
        for k, cell in sorted(self._cells.items()):
            base = dict(zip(self.labelnames, k))
            cum = 0
            for b, n in zip(self.bounds, cell.bucket_counts):
                cum += n
                out.append((self.name + "_bucket",
                            {**base, "le": _fmt_bound(b)}, float(cum)))
            out.append((self.name + "_bucket",
                        {**base, "le": "+Inf"}, float(cell.count)))
            out.append((self.name + "_sum", dict(base), cell.sum))
            out.append((self.name + "_count", dict(base),
                        float(cell.count)))
        return out


def _fmt_bound(b: float) -> str:
    return repr(b) if b != int(b) else str(int(b))


class MetricsRegistry:
    """Named metric directory.  ``counter``/``gauge``/``histogram`` are
    get-or-create (re-registration with a different kind or label set is
    an error — silent aliasing would merge unrelated series)."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, cls, name: str, help_text: str,
             labels: Tuple[str, ...], **kw) -> _Metric:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(self, name, help_text,
                                          tuple(labels), **kw)
            return m
        if type(m) is not cls or m.labelnames != tuple(labels):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind} with "
                f"labels {m.labelnames}")
        return m

    def counter(self, name: str, help_text: str = "",
                labels: Tuple[str, ...] = ()) -> Counter:
        return self._get(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: Tuple[str, ...] = ()) -> Gauge:
        return self._get(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  labels: Tuple[str, ...] = (),
                  bounds: Iterable[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._get(Histogram, name, help_text, labels,
                         bounds=bounds)

    def metrics(self) -> List[_Metric]:
        return [self._metrics[n] for n in sorted(self._metrics)]

    def snapshot(self) -> Dict[str, list]:
        """{name: [[labels, value], ...]} over every sample — plain JSON
        types (histograms expand into their _bucket/_sum/_count rows)."""
        out: Dict[str, list] = {}
        for m in self.metrics():
            for name, labels, value in m.samples():
                out.setdefault(name, []).append([labels, value])
        return out

    def reset(self) -> None:
        self._metrics.clear()


# One process-wide registry, mirroring spans._TRACER: serving-path call
# sites are one import away and a scrape sees the whole process.
_REGISTRY = MetricsRegistry(enabled=False)


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def metrics_enabled() -> bool:
    return _REGISTRY.enabled


def enable_metrics(enabled: bool = True,
                   reset: bool = False) -> MetricsRegistry:
    """Switch the global registry on/off.  Unlike span tracing, values
    are process-cumulative by design, so ``reset`` defaults False."""
    if reset:
        _REGISTRY.reset()
    _REGISTRY.enabled = enabled
    return _REGISTRY


def ingest_metrics() -> Tuple[Counter, Histogram, Gauge]:
    """The streaming-ingest family (engine/ingest.py), get-or-create on
    the global registry:

      * ``segments_prefetched_total`` — seams served from the completed
        prefetch buffer (the overlap worked);
      * ``ingest_stall_seconds`` — per-seam pipeline-blocking wall time
        (prefetch wait + any synchronous hard rebuild);
      * ``peak_device_trace_bytes`` — resident device trace footprint of
        the current run (2x segment bytes when double-buffered).
    """
    r = _REGISTRY
    return (
        r.counter("segments_prefetched_total",
                  "Segment seams served from the prefetch buffer"),
        r.histogram("ingest_stall_seconds",
                    "Pipeline-blocking seconds per segment seam",
                    bounds=INGEST_STALL_BUCKETS),
        r.gauge("peak_device_trace_bytes",
                "Device-resident trace bytes for the current run"),
    )


# ------------------------------------------------------------ exposition

def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_exposition(registry: Optional[MetricsRegistry] = None) -> str:
    """Prometheus text exposition format (version 0.0.4): HELP/TYPE
    headers per family, one ``name{labels} value`` line per sample."""
    registry = registry if registry is not None else _REGISTRY
    lines: List[str] = []
    for m in registry.metrics():
        lines.append(f"# HELP {m.name} {_escape(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for name, labels, value in m.samples():
            if labels:
                body = ",".join(f'{k}="{_escape(str(v))}"'
                                for k, v in labels.items())
                lines.append(f"{name}{{{body}}} {_fmt_value(value)}")
            else:
                lines.append(f"{name} {_fmt_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_exposition(text: str
                     ) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Inverse of render_exposition (for the formats this module emits):
    {sample_name: [(labels, value), ...]}.  Raises ValueError on a
    malformed line, so CI can assert the exposition PARSES."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, labels, rest = _parse_sample_head(line, lineno)
        rest = rest.strip()
        if not rest:
            raise ValueError(f"line {lineno}: missing value: {line!r}")
        try:
            value = float(rest.split()[0])
        except ValueError as e:
            raise ValueError(f"line {lineno}: bad value {rest!r}") from e
        out.setdefault(name, []).append((labels, value))
    return out


def _parse_sample_head(line: str, lineno: int):
    brace = line.find("{")
    if brace < 0:
        name, _, rest = line.partition(" ")
        if not name:
            raise ValueError(f"line {lineno}: no metric name: {line!r}")
        return name, {}, rest
    name = line[:brace]
    end = _find_close_brace(line, brace, lineno)
    labels = _parse_labels(line[brace + 1:end], lineno)
    return name, labels, line[end + 1:]


def _find_close_brace(line: str, brace: int, lineno: int) -> int:
    in_quote = False
    i = brace + 1
    while i < len(line):
        c = line[i]
        if in_quote:
            if c == "\\":
                i += 1
            elif c == '"':
                in_quote = False
        elif c == '"':
            in_quote = True
        elif c == "}":
            return i
        i += 1
    raise ValueError(f"line {lineno}: unterminated label set: {line!r}")


def _parse_labels(body: str, lineno: int) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.find("=", i)
        if eq < 0:
            if body[i:].strip(", "):
                raise ValueError(
                    f"line {lineno}: trailing label junk {body[i:]!r}")
            break
        key = body[i:eq].strip().lstrip(",").strip()
        if body[eq + 1:eq + 2] != '"':
            raise ValueError(f"line {lineno}: unquoted label value")
        j = eq + 2
        val: List[str] = []
        while j < len(body) and body[j] != '"':
            if body[j] == "\\" and j + 1 < len(body):
                nxt = body[j + 1]
                val.append({"n": "\n", "\\": "\\", '"': '"'}
                           .get(nxt, "\\" + nxt))
                j += 2
            else:
                val.append(body[j])
                j += 1
        if j >= len(body):
            raise ValueError(f"line {lineno}: unterminated label value")
        labels[key] = "".join(val)
        i = j + 1
    return labels


def write_exposition(path: str,
                     registry: Optional[MetricsRegistry] = None) -> None:
    """Atomically (tmp + rename) write the exposition to ``path`` — a
    scraper or `cat` mid-drain never sees a torn file."""
    import os
    import tempfile
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.prom")
    pending = tmp
    try:
        with os.fdopen(fd, "w") as f:
            f.write(render_exposition(registry))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        pending = None
    finally:
        if pending is not None:
            try:
                os.unlink(pending)
            except OSError:
                pass
