"""Host-side span tracing for the simulator's own driver path.

The engine's device work is profiled by the round-metric gauges
(obs/metrics.py); everything outside the jitted step — config
resolution, trace load/annotation, jit compile, each polling-window
dispatch — is wall-clock host work that used to require hand-rolled
differencing (tools/profile_round.py) to attribute.  A ``SpanTracer``
records nestable begin/end wall-clock events from ``with span(...)``
context managers and renders them as Chrome trace-event ``X`` slices
(obs/export.chrome_trace).

Disabled-path cost: ``span()`` on a disabled tracer is one attribute
check returning a shared no-op context manager — no allocation, no
clock read — so instrumentation can stay in the driver unconditionally.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, NamedTuple, Optional


class SpanEvent(NamedTuple):
    """One completed span (wall-clock, nanoseconds since tracer epoch)."""

    name: str
    t0_ns: int
    dur_ns: int
    depth: int
    args: Optional[Dict[str, Any]]


class _NullSpan:
    """Shared reentrant no-op context manager (the disabled path)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """Context manager recording one span into its tracer on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._tracer._depth += 1
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        tr = self._tracer
        tr._depth -= 1
        tr._record(SpanEvent(
            name=self._name, t0_ns=self._t0 - tr.epoch_ns,
            dur_ns=t1 - self._t0, depth=tr._depth, args=self._args))
        return False


class SpanTracer:
    """Collects nested wall-clock spans; exported via obs/export.

    Events are appended at span EXIT (a parent therefore follows its
    children in ``events``); ``t0_ns`` is relative to the tracer's epoch
    so runs serialize with stable small timestamps.

    ``events`` is BOUNDED (``max_events``, default 64k): an always-on
    tracer inside a long-lived serving process must not grow without
    limit.  Past the cap, new spans are counted in ``dropped`` (and the
    process-wide ``spans_dropped_total`` registry counter) instead of
    recorded — the oldest spans win because they hold the compile story
    a drain's timeline is usually read for.
    """

    DEFAULT_MAX_EVENTS = 65536

    def __init__(self, enabled: bool = False,
                 max_events: int = DEFAULT_MAX_EVENTS):
        self.enabled = enabled
        self.epoch_ns = time.perf_counter_ns()
        self.events: List[SpanEvent] = []
        self.max_events = max_events
        self.dropped = 0
        self._depth = 0

    def span(self, name: str, **args):
        if not self.enabled:
            return _NULL_SPAN
        return _LiveSpan(self, name, args or None)

    def _record(self, event: SpanEvent) -> None:
        if len(self.events) >= self.max_events:
            self.dropped += 1
            from graphite_tpu.obs.registry import get_registry
            get_registry().counter(
                "spans_dropped_total",
                "spans discarded past SpanTracer.max_events").inc()
            return
        self.events.append(event)

    def clear(self) -> None:
        self.events = []
        self.dropped = 0
        self._depth = 0

    def mark(self) -> int:
        """Cursor into ``events`` for slicing one phase's spans later."""
        return len(self.events)

    def since(self, mark: int) -> List[SpanEvent]:
        return self.events[mark:]


# One process-wide tracer: the driver path is single-threaded host code,
# and a global keeps the instrumentation call sites one import away.
_TRACER = SpanTracer(enabled=False)


def get_tracer() -> SpanTracer:
    return _TRACER


def tracing_enabled() -> bool:
    return _TRACER.enabled


def enable_tracing(enabled: bool = True, reset: bool = True) -> SpanTracer:
    """Switch the global tracer on/off (fresh epoch/events by default)."""
    if reset:
        _TRACER.clear()
        _TRACER.epoch_ns = time.perf_counter_ns()
    _TRACER.enabled = enabled
    return _TRACER


def span(name: str, **args):
    """``with span("trace.load", path=...):`` on the global tracer."""
    return _TRACER.span(name, **args)
