"""Run telemetry: host span tracing, device round metrics, exports.

The reference treats observability of the *simulated machine* as
first-class (StatisticsManager sampling, progress trace, Log framework —
statistics_manager.cc:41-114, pin/progress_trace.cc, common/misc/log.h);
engine/sim.py + engine/quantum.py carry those over.  This package adds
observability of the *simulator itself*:

  * ``spans`` — nestable host-side wall-clock span tracing for the driver
    path (config resolution, trace load, jit compile, each polling-window
    dispatch).  Near-zero overhead when disabled: one attribute check and
    a shared no-op context manager.
  * ``metrics`` — the device round-metric series sampled at quantum
    boundaries by engine/quantum._maybe_sample when [telemetry] is
    enabled (engine-health gauges: events retired, stall-reason
    breakdown, quanta/round counters, clock skew) plus per-tile
    progress/occupancy snapshots.
  * ``export`` — a machine-readable RunReport JSON (superset of the text
    summary; consumed by bench.py / tools/results_db.py) and a Chrome
    trace-event / Perfetto JSON merging host wall-clock span tracks with
    per-tile simulated-time tracks (plus, when given tickets, the sweep
    service's per-ticket lifecycle track on the same wall-clock axis).
  * ``registry`` — process-wide SERVICE metrics (counters, gauges,
    fixed-bucket histograms with labels): the sweep service's ticket
    latencies, cache-hit ratio, and per-state gauges, rendered as a
    Prometheus text exposition + JSON snapshot.  Same null-path
    discipline as spans: one attribute check when disabled.
"""

from graphite_tpu.obs.spans import (  # noqa: F401
    SpanTracer, enable_tracing, get_tracer, span, tracing_enabled)
from graphite_tpu.obs.metrics import TEL_SERIES  # noqa: F401
from graphite_tpu.obs.registry import (  # noqa: F401
    MetricsRegistry, enable_metrics, get_registry, metrics_enabled,
    parse_exposition, render_exposition, write_exposition)
from graphite_tpu.obs.export import (  # noqa: F401
    RUN_REPORT_SCHEMA, build_run_report, chrome_trace, ticket_events,
    write_telemetry_dir)
