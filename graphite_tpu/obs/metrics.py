"""Device round-metric series: names + derived views.

The gauges themselves are sampled ON DEVICE at quantum boundaries by
engine/quantum._maybe_sample (the same lax.cond hook that feeds the
statistics/progress/power rings, so telemetry adds no fused-loop
branches); this module is the host-side contract: the ordered series
names matching the rows of ``SimState.tel_gauges``, and the derived
per-window rates the exports publish.

All series are CUMULATIVE except the ``stall_*`` / ``tiles_done`` /
``clock_*`` instantaneous gauges; ``derive_rates`` differences the
cumulative ones into per-sample-window rates (events retired per round,
quanta per window, ...).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

# Row order of SimState.tel_gauges ([len(TEL_SERIES), samples] int64).
TEL_SERIES = (
    "events_retired",     # cumulative: sum of trace cursors over all
    #                       streams (stream store folded in under the
    #                       ThreadScheduler, so rotations keep it monotone)
    "instructions",       # cumulative: sum of icount counters
    "tiles_done",         # instantaneous: streams that are DONE
    "stall_mem",          # instantaneous: tiles parked on SH/EX/IFETCH
    "stall_sync",         # instantaneous: tiles parked on sync objects
    "stall_msg",          # instantaneous: tiles parked on CAPI send/recv
    "quanta",             # cumulative: quantum steps executed
    "rounds_window",      # cumulative: block-window retirement rounds
    "rounds_complex",     # cumulative: complex (one-event) slots
    "conflict_rounds",    # cumulative: directory conflict rounds
    "resolve_calls",      # cumulative: resolve passes
    "clock_min_ps",       # instantaneous: slowest tile clock
    "clock_max_ps",       # instantaneous: fastest tile clock (skew = max-min)
)

_CUMULATIVE = ("events_retired", "instructions", "quanta", "rounds_window",
               "rounds_complex", "conflict_rounds", "resolve_calls")


def derive_rates(series: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Per-window rates from the cumulative series (length n-1 each):
    the engine-health numbers PROFILE.md derives by hand — events retired
    per round, rounds per quantum, quanta per sample window — plus the
    instantaneous clock skew (clock_max − clock_min, length n: the
    lax-barrier slack the fast-forward span budget trades against)."""
    out: Dict[str, np.ndarray] = {}
    for name in _CUMULATIVE:
        if name in series and len(series[name]) >= 2:
            out[f"d_{name}"] = np.diff(np.asarray(series[name]))
    if "d_events_retired" in out and "d_rounds_window" in out:
        rounds = out["d_rounds_window"] + out.get(
            "d_rounds_complex", np.zeros_like(out["d_rounds_window"]))
        # A sample window with ZERO rounds (an idle window between two
        # samples, or a fast-forwarded span) must read 0 events/round,
        # not d_events/1 — guard the division explicitly.
        out["events_per_round"] = np.where(
            rounds > 0,
            out["d_events_retired"] / np.maximum(rounds, 1), 0.0)
    if "clock_max_ps" in series and "clock_min_ps" in series:
        out["clock_skew_ps"] = (np.asarray(series["clock_max_ps"])
                                - np.asarray(series["clock_min_ps"]))
    return out
