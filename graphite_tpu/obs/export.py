"""RunReport + Chrome trace-event (Perfetto) export.

Two artifacts per run:

  * **RunReport JSON** — a machine-readable superset of the text summary
    (engine/sim.SimSummary.render): aggregate counters, VM footprints,
    completion time, host spans, and the sampled round-metric series.
    Stable top-level keys; directly consumable by bench.py and
    tools/results_db.py (which reads num_tiles/kind/mips/host_seconds/
    completion_time_ns from any row dict).
  * **Chrome trace-event JSON** — the ``{"traceEvents": [...]}`` format
    chrome://tracing and https://ui.perfetto.dev load: host wall-clock
    spans as ``X`` slices on one process track, per-tile simulated-time
    slices (derived from the telemetry/progress samples) on another.
    The two tracks deliberately share one timeline with different
    units — host microseconds vs simulated microseconds — the same way
    the reference's progress trace and host logs sit side by side.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from graphite_tpu.obs.metrics import TEL_SERIES, derive_rates
from graphite_tpu.time_base import ps_to_ns

RUN_REPORT_SCHEMA = "graphite_tpu/run_report@1"

HOST_PID = 1        # host driver (wall clock) process track
DEVICE_PID = 2      # simulated device time process track
SERVICE_PID = 3     # sweep-service ticket lifecycle (wall clock) track

# JSON-embedded per-tile matrices are capped (flagged, never silent):
# a 1024-tile x 1024-sample cursor matrix would dominate the report.
MAX_PER_TILE_CELLS = 65536
# Per-tile slice tracks in the Chrome trace are capped the same way.
MAX_TILE_TRACKS = 256


def _jlist(a) -> list:
    return [int(v) for v in np.asarray(a).reshape(-1)]


def build_run_report(summary, tracer=None, workload: Optional[str] = None,
                     extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Fold a SimSummary (+ optional SpanTracer) into the RunReport dict.
    Everything inside is plain JSON types (round-trips json.dumps/loads)."""
    agg = {k: int(v.sum()) for k, v in summary.counters.items()}
    completed = bool(summary.done.all())
    report: Dict[str, Any] = {
        "schema": RUN_REPORT_SCHEMA,
        "workload": workload,
        "kind": "completed" if completed else "bounded",
        "num_tiles": int(summary.params.num_tiles),
        "all_done": completed,
        "completion_time_ps": int(summary.completion_time_ps),
        "completion_time_ns": float(ps_to_ns(summary.completion_time_ps)),
        "host_seconds": float(summary.host_seconds),
        "device_steps": int(summary.steps),
        "quanta": int(summary.quanta),
        "total_instructions": int(summary.total_instructions),
        # MIPS only for completed runs (bench.py's honesty rule).
        "mips": float(summary.simulated_mips) if completed else None,
        "counters": agg,
        "vm": summary.vm_summary(),
        "spans": spans_to_json(tracer.events) if tracer is not None else [],
    }
    tel = summary.telemetry_trace()
    if tel is not None:
        series = {k: _jlist(v) for k, v in tel.items() if k != "time_ps"}
        telemetry: Dict[str, Any] = {
            "time_ps": _jlist(tel["time_ps"]),
            "series": series,
            "rates": {k: [float(x) for x in v]
                      for k, v in derive_rates(tel).items()},
        }
        cursor = summary.tel_cursor_trace()
        if cursor is not None:
            if cursor.size <= MAX_PER_TILE_CELLS:
                telemetry["per_tile_events"] = [
                    _jlist(row) for row in cursor]
                telemetry["per_tile_pend"] = [
                    _jlist(row) for row in summary.tel_pend_trace()]
            else:
                telemetry["per_tile_omitted"] = True
        report["telemetry"] = telemetry
    # Streaming-ingest roll-up (round 16): seams, stall seconds/fraction,
    # prefetch hit counts, peak device trace bytes.  Whole-trace runs
    # (and summary shapes without the accessor) omit the section.
    ing = getattr(summary, "ingest_section", None)
    if ing is not None:
        ing = ing()
        if ing is not None:
            report["ingest"] = ing
    if extra:
        report.update(extra)
    return report


def spans_to_json(events) -> List[Dict[str, Any]]:
    return [{"name": e.name, "ts_us": e.t0_ns / 1e3,
             "dur_us": e.dur_ns / 1e3, "depth": e.depth,
             "args": e.args or {}} for e in events]


def _host_events(tracer) -> List[Dict[str, Any]]:
    ev: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": HOST_PID, "tid": 0,
        "args": {"name": "host driver (wall clock)"}}]
    for e in tracer.events:
        ev.append({"name": e.name, "cat": "host", "ph": "X",
                   "ts": e.t0_ns / 1e3, "dur": e.dur_ns / 1e3,
                   "pid": HOST_PID, "tid": 0, "args": e.args or {}})
    return ev


def _device_events(summary) -> List[Dict[str, Any]]:
    """Per-tile simulated-time slices + aggregate counter tracks from the
    sampled series (telemetry cursor snapshots when available, otherwise
    the progress-trace icount snapshots)."""
    tel = summary.telemetry_trace()
    per_tile = summary.tel_cursor_trace()
    unit = "events"
    if per_tile is None and getattr(summary.params, "progress_enabled",
                                    False):
        tr = summary.stats_trace()
        per_tile = np.asarray(tr.get("tile_icount"))
        times = np.asarray(tr["time_ps"])
        unit = "instr"
    elif per_tile is not None:
        times = np.asarray(tel["time_ps"])
    else:
        return []
    if per_tile is None or len(times) == 0:
        return []

    ev: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": DEVICE_PID, "tid": 0,
        "args": {"name": "device (simulated time)"}}]
    n, T = per_tile.shape
    shown = min(T, MAX_TILE_TRACKS)
    # Prepend the t=0 origin so the first sample window is a slice too.
    t_edges = np.concatenate([[0], times])
    deltas = np.diff(np.concatenate(
        [np.zeros((1, T), dtype=per_tile.dtype), per_tile], axis=0), axis=0)
    for t in range(shown):
        ev.append({"ph": "M", "name": "thread_name", "pid": DEVICE_PID,
                   "tid": t, "args": {"name": f"tile {t}"}})
        for i in range(n):
            d = int(deltas[i, t])
            if d <= 0:
                continue
            ts0, ts1 = int(t_edges[i]), int(t_edges[i + 1])
            ev.append({"name": f"{d} {unit}", "cat": "tile", "ph": "X",
                       "ts": ts0 / 1e6, "dur": max(ts1 - ts0, 1) / 1e6,
                       "pid": DEVICE_PID, "tid": t, "args": {unit: d}})
    if tel is not None:
        for cname in ("events_retired", "tiles_done"):
            for i in range(len(times)):
                ev.append({"name": cname, "ph": "C", "pid": DEVICE_PID,
                           "tid": 0, "ts": int(times[i]) / 1e6,
                           "args": {"value": int(tel[cname][i])}})
    if shown < T:
        ev.append({"ph": "M", "name": "process_labels", "pid": DEVICE_PID,
                   "tid": 0,
                   "args": {"labels": f"showing {shown}/{T} tiles"}})
    return ev


# Ticket lifecycle phases rendered as slices, in timeline order.  Each
# entry is (slice name, start mark, set of end marks — first present
# wins).  Marks are Ticket.marks keys (perf_counter seconds), recorded
# by sweep/service.py on live transitions.
_TICKET_PHASES = (
    ("queued", "submit", ("running", "first_result", "done")),
    ("running", "running", ("first_result", "done")),
    ("streaming", "first_result", ("done",)),
)


def ticket_events(tickets, epoch_ns: Optional[int] = None
                  ) -> List[Dict[str, Any]]:
    """Chrome-trace slices for sweep-service ticket lifecycles: one tid
    per ticket on the SERVICE_PID track, phases queued/running/streaming
    as X slices, terminal status in args.  ``tickets`` is any iterable
    of sweep.service.Ticket; only tickets with live (this-process) marks
    render — replayed tickets carry wall-clock times from a dead
    process, which share no timeline with the current tracer.  With
    ``epoch_ns`` from a SpanTracer, ticket slices land on the SAME
    wall-clock axis as the host spans (both derive from perf_counter),
    so a drain renders as one timeline."""
    items = [t for t in tickets if getattr(t, "marks", None)]
    if not items:
        return []
    if epoch_ns is None:
        epoch_ns = int(min(min(t.marks.values()) for t in items) * 1e9)
    ev: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": SERVICE_PID, "tid": 0,
        "args": {"name": "sweep service (wall clock)"}}]
    for t in sorted(items, key=lambda t: t.ticket):
        ev.append({"ph": "M", "name": "thread_name", "pid": SERVICE_PID,
                   "tid": t.ticket,
                   "args": {"name": f"ticket {t.ticket} [{t.label}]"}})
        for phase, start, ends in _TICKET_PHASES:
            if start not in t.marks:
                continue
            end = next((t.marks[e] for e in ends if e in t.marks), None)
            if end is None or end < t.marks[start]:
                continue
            ev.append({
                "name": phase, "cat": "ticket", "ph": "X",
                "ts": (t.marks[start] * 1e9 - epoch_ns) / 1e3,
                "dur": (end - t.marks[start]) * 1e6,
                "pid": SERVICE_PID, "tid": t.ticket,
                "args": {"ticket": t.ticket, "label": t.label,
                         "status": t.status,
                         "from_cache": bool(t.from_cache)}})
    return ev


def chrome_trace(summary=None, tracer=None, tickets=None
                 ) -> Dict[str, Any]:
    """Build the Chrome trace-event JSON dict (loadable by Perfetto /
    chrome://tracing): ``traceEvents`` of X/C/M phase events with
    ts (microseconds), pid, tid.  ``tickets`` adds the sweep-service
    lifecycle track beside the host spans (same wall-clock axis)."""
    events: List[Dict[str, Any]] = []
    if tracer is not None and tracer.events:
        events.extend(_host_events(tracer))
    if summary is not None:
        events.extend(_device_events(summary))
    if tickets is not None:
        events.extend(ticket_events(
            tickets,
            epoch_ns=tracer.epoch_ns if tracer is not None else None))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ns",
        "otherData": {
            "generator": "graphite_tpu.obs",
            "host_track_unit": "wall-clock us",
            "device_track_unit": "simulated us",
        },
    }


def write_telemetry_dir(dirpath: str, summary, tracer=None,
                        workload: Optional[str] = None,
                        prefix: str = "run",
                        extra: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, str]:
    """Write ``<prefix>_report.json`` + ``<prefix>_trace.json`` under
    ``dirpath`` (created if needed); returns the paths."""
    os.makedirs(dirpath, exist_ok=True)
    report_path = os.path.join(dirpath, f"{prefix}_report.json")
    trace_path = os.path.join(dirpath, f"{prefix}_trace.json")
    with open(report_path, "w") as f:
        json.dump(build_run_report(summary, tracer=tracer,
                                   workload=workload, extra=extra), f)
    with open(trace_path, "w") as f:
        json.dump(chrome_trace(summary=summary, tracer=tracer), f)
    return {"report": report_path, "trace": trace_path}
