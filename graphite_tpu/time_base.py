"""Simulated-time conventions.

Simulated time is an int64 count of **picoseconds**, matching the
reference's Time type (reference: common/misc/time_types.h:7-60).  Model
latencies are specified in cycles at some module frequency (GHz) and
converted to picoseconds at use, matching the reference's frequency-aware
Latency type (time_types.h Latency).

Inside jitted kernels, frequencies ride along as float64 arrays (per tile
or per DVFS domain) so DVFS can change them at run time; conversions
round-half-up like the reference's double->UInt64 conversion.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

PS_PER_NS = 1000
PS_PER_US = 1000_000
PS_PER_S = 10**12

# A sentinel "never" time for wakeup lists / termination checks.
TIME_MAX = np.int64(2**62)


def cycles_to_ps(cycles, freq_ghz):
    """Host-side: cycle count at ``freq_ghz`` -> int64 picoseconds.

    cycles * period_ps(freq) with the same integer period the engine
    stores (state.period_ps) — device code multiplies integer periods
    directly and never sees floats.
    """
    return np.int64(cycles) * np.int64(round(PS_PER_NS / float(freq_ghz)))


def ps_to_cycles(ps, freq_ghz):
    """Host-side: int64 picoseconds -> cycle count at ``freq_ghz``
    (rounded against the engine's integer period)."""
    p = np.int64(round(PS_PER_NS / float(freq_ghz)))
    return np.int64((np.int64(ps) + p // 2) // p)


def ns_to_ps(ns) -> np.int64:
    return np.int64(round(float(ns) * PS_PER_NS))


def ps_to_ns(ps) -> float:
    return float(ps) / PS_PER_NS


def period_ps(freq_ghz) -> int:
    """Integer picoseconds per cycle at ``freq_ghz`` — the engine's clock
    convention (state.period_ps stores exactly this value per module)."""
    return int(round(PS_PER_NS / float(freq_ghz)))
