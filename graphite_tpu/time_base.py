"""Simulated-time conventions.

Simulated time is an int64 count of **picoseconds**, matching the
reference's Time type (reference: common/misc/time_types.h:7-60).  Model
latencies are specified in cycles at some module frequency (GHz) and
converted to picoseconds at use, matching the reference's frequency-aware
Latency type (time_types.h Latency).

Inside jitted kernels, frequencies ride along as float64 arrays (per tile
or per DVFS domain) so DVFS can change them at run time; conversions
round-half-up like the reference's double->UInt64 conversion.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

PS_PER_NS = 1000
PS_PER_US = 1000_000
PS_PER_S = 10**12

# A sentinel "never" time for wakeup lists / termination checks.
TIME_MAX = np.int64(2**62)


def cycles_to_ps(cycles, freq_ghz):
    """Convert a cycle count at ``freq_ghz`` to int64 picoseconds.

    ps = cycles * 1000 / freq_ghz, rounded to nearest (reference converts
    through double ns; we keep float64 which is exact for all practical
    cycle counts < 2**52).
    """
    return jnp.int64(jnp.round(jnp.float64(cycles) * (PS_PER_NS / 1.0) / jnp.float64(freq_ghz)))


def ps_to_cycles(ps, freq_ghz):
    """Convert int64 picoseconds to a cycle count at ``freq_ghz`` (rounded)."""
    return jnp.int64(jnp.round(jnp.float64(ps) * jnp.float64(freq_ghz) / PS_PER_NS))


def ns_to_ps(ns) -> np.int64:
    return np.int64(round(float(ns) * PS_PER_NS))


def ps_to_ns(ps) -> float:
    return float(ps) / PS_PER_NS


def period_ps(freq_ghz) -> float:
    """Picoseconds per cycle at ``freq_ghz`` (float; multiply then round)."""
    return PS_PER_NS / float(freq_ghz)
